// C++ scalar oracle — the CPU reference engine of the framework.
//
// Plays the role the Rust implementation plays in the reference
// (`2892931976/consensus-rs`, SURVEY.md §2 components 1-12): a sequential,
// per-node implementation of each consensus protocol against which the
// batched JAX/TPU engine is checked for decided-log BYTE-equivalence
// (BASELINE.json:2,5). Implements docs/SPEC.md exactly — every phase,
// tie-break, and threefry draw. Exposed to Python via a C ABI (ctypes;
// pybind11 is not available in this environment).
//
// Build: `make -C cpp` → liboracle.so.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <limits>
#include <memory>
#include <string>
#include <vector>

#include "engine.h"
#include "threefry.h"

namespace ctpu {
namespace {

constexpr uint32_t ROLE_F = 0, ROLE_C = 1, ROLE_L = 2;
constexpr int32_t NONE = -1;

// SimConfig::oracle_delivery values (engine.h): how Net answers queries.
constexpr uint32_t DELIVERY_AUTO = 0, DELIVERY_DENSE = 1, DELIVERY_EDGE = 2;

// Per-round delivery decisions (SPEC §2), in one of two byte-identical
// strategies:
//
//  * DENSE — materialize the full [N, N] matrix once per round. Each
//    directed edge is queried up to ~7 times per round across the dense
//    engines' phases, so paying the mixer chain once per edge is the
//    right trade when ~every edge is live (the pre-edge-wise design,
//    still the single-core baseline for the dense SPEC §3 / §6 rounds).
//  * EDGE — answer each query on demand from the counter-based draw.
//    The capped engines (SPEC §3b Raft, Paxos with few proposers) only
//    ever query O(A·N) live edges, so the O(N²) materialization was
//    pure waste — 10 GB and ~10¹⁰ mixer chains per 100k-node run that
//    the queries never looked at (VERDICT r5 missing #1). The per-sender
//    absorb ``hi[i]`` is hoisted once per round (O(N)), so a query is
//    one absorb + one finalize + the partition side check.
//
// Both strategies evaluate the SAME pure function of (seed, r, i, j) —
// the mixer chain and the partition side draws are keyed by absolute
// ids — so digests cannot depend on the choice (tested per engine in
// tests/test_oracle_delivery.py).
struct Net {
  uint32_t n = 0;
  uint32_t drop_cut = 0;
  uint32_t max_delay = 0;  // SPEC §A.2 retransmission horizon (0 = off)
  uint64_t seed = 0;
  uint32_t r = 0;
  bool part_active = false;
  bool edge_mode = false;
  // SPEC §6c: when non-null, up[i] == 0 (a down node) kills every edge
  // touching i — down nodes neither send nor receive.
  const uint8_t* up = nullptr;
  std::vector<uint8_t> side;  // [n]; filled only when part_active
  std::vector<uint32_t> hi;   // [n] edge mode: per-sender hoisted absorb
  std::vector<uint8_t> mat;   // [n*n] dense mode: delivered?

  void begin_round(uint64_t seed_, uint32_t n_, uint32_t r_,
                   uint32_t drop_cut_, uint32_t part_cut, bool edge,
                   uint32_t max_delay_ = 0, const uint8_t* up_ = nullptr) {
    n = n_;
    drop_cut = drop_cut_;
    max_delay = max_delay_;
    seed = seed_;
    r = r_;
    edge_mode = edge;
    up = up_;
    part_active = random_u32(seed, STREAM_PARTITION, r, 0, 0) < part_cut;
    if (part_active) {
      side.resize(n);
      for (uint32_t i = 0; i < n; ++i)
        side[i] = random_u32(seed, STREAM_PARTITION, r, 1, i) & 1u;
    }
    const uint32_t hr = mix_absorb(
        static_cast<uint32_t>(seed & 0xFFFFFFFFull) ^ STREAM_DELIVER, r);
    if (edge_mode) {
      mat.clear();
      hi.resize(n);
      for (uint32_t i = 0; i < n; ++i) hi[i] = mix_absorb(hr, i);
      return;
    }
    mat.assign(size_t(n) * n, 0);
    for (uint32_t i = 0; i < n; ++i) {
      if (up && !up[i]) continue;
      const uint32_t h = mix_absorb(hr, i);
      for (uint32_t j = 0; j < n; ++j) {
        if (i == j) continue;
        if (up && !up[j]) continue;
        // SPEC §2 drop leg, repaired by a §A.2 delayed retransmission;
        // partitions are topology faults — never repaired.
        bool open = mix_fin(mix_absorb(h, j)) >= drop_cut;
        if (!open && max_delay)
          open = delayed_open(seed, r, i, j, drop_cut, max_delay);
        if (!open) continue;
        if (part_active && side[i] != side[j]) continue;
        mat[size_t(i) * n + j] = 1;
      }
    }
  }
  // The SPEC §2 edge decision for i → j (drop ∘ §A.2 delayed
  // retransmission ∘ partition ∘ §6c down endpoints ∘ no-self).
  bool edge(uint32_t i, uint32_t j) const {
    if (i == j) return false;
    if (up && (!up[i] || !up[j])) return false;
    bool open = mix_fin(mix_absorb(hi[i], j)) >= drop_cut;
    if (!open && max_delay)
      open = delayed_open(seed, r, i, j, drop_cut, max_delay);
    if (!open) return false;
    return !part_active || side[i] == side[j];
  }
  bool delivered(uint32_t i, uint32_t j) const {
    if (edge_mode) return edge(i, j);
    return mat[size_t(i) * n + j] != 0;
  }
};

inline bool churn_fires(uint64_t seed, uint32_t r, uint32_t cut) {
  return random_u32(seed, STREAM_CHURN, r, 0, 0) < cut;
}

// SPEC §9 in-network vote aggregation — the scalar twin of
// ops/aggregate.py (net_model="switch"). K aggregator vertices
// partition the population into contiguous segments (a(i) = i / B,
// B = ceil(N/K)); vote responses travel sender → aggregator (uplink,
// the sender's §2 edge draw at the aggregator's effective round) →
// receiver (downlink at the current round). STREAM_AGG drives the
// per-(round, aggregator) fault axes: failure (a down aggregator
// silently drops its whole segment) and stale state (the uplink
// re-draws against a shifted round key r - d, d <= max_stale — a pure
// re-draw, §A.2-style; values/contributions stay current-round).
// Aggregator a of phase ph is the synthetic vertex N + ph*K + a; its
// partition SIDE is keyed on the phase-independent vertex N + a.
struct AggNet {
  bool on = false;
  uint32_t N = 0, K = 1, B = 1;
  uint32_t drop_cut = 0, part_cut = 0, max_delay = 0;
  // SPEC §9b poisoned-combine knobs — set once by the owning Sim
  // before the run (begin_round never touches them; every §9b draw
  // keys on the live round r, so there is no per-round state).
  uint32_t agg_byz = 0, poison_cut = 0, uplink_cut = 0;
  uint64_t seed = 0;
  uint32_t r = 0;
  std::vector<uint8_t> alive;  // [K]
  std::vector<uint32_t> q;     // [K] effective uplink round

  uint32_t agg_of(uint32_t i) const { return i / B; }

  // §9b forged-combine activation: the LAST agg_byz aggregator ids are
  // byzantine (the node-side tail convention); each fires per (round,
  // phase-qualified vertex) via STREAM_POISON c0 = 0 — the same phase
  // qualification as the vertex's edge draws, so the two pbft vote
  // phases equivocate independently. Liveness is NOT checked here:
  // down() already folds alive, and a dead aggregator serves nothing.
  bool poisoned(uint32_t ph, uint32_t a) const {
    if (!poison_cut || a + agg_byz < K) return false;
    return random_u32(seed, STREAM_POISON, r, 0, ph * K + a) < poison_cut;
  }
  // §9b uplink-lie activation (c0 = 1, one claim per (round, node) —
  // shared by every phase and slot) and the forged value it serves
  // (c0 = 2, the same 32-bit payload discipline as STREAM_VALUE).
  // The byzantine-sender mask is the caller's guard.
  bool lies(uint32_t i) const {
    return uplink_cut &&
           random_u32(seed, STREAM_POISON, r, 1, i) < uplink_cut;
  }
  uint32_t lie_val(uint32_t i) const {
    return random_u32(seed, STREAM_POISON, r, 2, i);
  }
  // Full segment population — the forged count a poisoned aggregator
  // serves (§9b: it claims its ENTIRE segment voted the receiver's
  // value). The last segment may be a remainder.
  uint32_t width(uint32_t a) const {
    const uint32_t lo = a * B;
    return lo >= N ? 0 : std::min(B, N - lo);
  }

  void begin_round(uint64_t seed_, uint32_t n, uint32_t k, uint32_t r_,
                   uint32_t drop_cut_, uint32_t part_cut_,
                   uint32_t max_delay_, uint32_t fail_cut,
                   uint32_t stale_cut, uint32_t max_stale) {
    on = true;
    seed = seed_;
    N = n;
    K = k;
    B = (n + k - 1) / k;
    drop_cut = drop_cut_;
    part_cut = part_cut_;
    max_delay = max_delay_;
    r = r_;
    alive.assign(K, 1);
    q.assign(K, r);
    for (uint32_t a = 0; a < K; ++a) {
      alive[a] = !(random_u32(seed, STREAM_AGG, r, 0, a) < fail_cut);
      const bool stale = random_u32(seed, STREAM_AGG, r, 1, a) < stale_cut;
      const uint32_t d =
          1 + random_u32(seed, STREAM_AGG, r, 2, a) % max_stale;
      if (stale && r >= d) q[a] = r - d;  // round keys must not wrap
    }
  }

  bool part_pair_ok(uint32_t rq, uint32_t va, uint32_t vb) const {
    if (!part_cut) return true;
    if (!(random_u32(seed, STREAM_PARTITION, rq, 0, 0) < part_cut))
      return true;
    return (random_u32(seed, STREAM_PARTITION, rq, 1, va) & 1u) ==
           (random_u32(seed, STREAM_PARTITION, rq, 1, vb) & 1u);
  }

  bool open_edge(uint32_t rq, uint32_t src, uint32_t dst) const {
    bool open = delivery_u32(seed, rq, src, dst) >= drop_cut;
    if (!open && max_delay)
      open = delayed_open(seed, rq, src, dst, drop_cut, max_delay);
    return open;
  }

  // Edge-model uplink: sender i → its aggregator, phase ph.
  bool up_edge(uint32_t ph, uint32_t i) const {
    const uint32_t a = agg_of(i), rq = q[a];
    return open_edge(rq, i, N + ph * K + a) &&
           part_pair_ok(rq, i, N + a);
  }
  // §6b bcast uplink: the sender's one atomic broadcast draw (q, i, i).
  bool up_bcast(uint32_t i) const {
    const uint32_t a = agg_of(i), rq = q[a];
    return open_edge(rq, i, i) && part_pair_ok(rq, i, N + a);
  }
  // Downlink: aggregator a → receiver j at the CURRENT round.
  bool down(uint32_t ph, uint32_t a, uint32_t j) const {
    if (!alive[a]) return false;
    return open_edge(r, N + ph * K + a, j) && part_pair_ok(r, N + a, j);
  }
  // The factorized two-hop for an edge-model vote flight i → j.
  bool two_hop(uint32_t ph, uint32_t i, uint32_t j) const {
    return up_edge(ph, i) && down(ph, agg_of(i), j);
  }
};

// SPEC §6c crash-recover transitions — the scalar twin of
// ops/adversary.crash_transition. Both draws are pure counter
// functions of (seed, round, node); only the down mask is history.
// Order within the round: recoveries decided on the start-of-round
// down set, crashes on the post-recovery up set, the max_crashed cap
// admitting would-be crashers in ascending id order.
struct CrashAdv {
  bool on = false;
  std::vector<uint8_t> down, up, rec;

  void init(uint32_t n, uint32_t crash_cut) {
    on = crash_cut > 0;
    down.assign(n, 0);
    up.assign(n, 1);
    rec.assign(n, 0);
  }
  const uint8_t* up_mask() const { return on ? up.data() : nullptr; }
  bool is_down(uint32_t i) const { return on && down[i]; }

  void advance(uint64_t seed, uint32_t r, uint32_t crash_cut,
               uint32_t recover_cut, uint32_t max_crashed) {
    if (!on) return;
    const uint32_t n = uint32_t(down.size());
    uint32_t still_cnt = 0;
    for (uint32_t i = 0; i < n; ++i) {
      rec[i] = down[i] &&
               random_u32(seed, STREAM_CRASH, r, 1, i) < recover_cut;
      if (down[i] && !rec[i]) ++still_cnt;
    }
    uint32_t rank = 0;  // cumsum over the RAW want mask, id-ascending
    for (uint32_t i = 0; i < n; ++i) {
      const bool still = down[i] && !rec[i];
      bool want = !still &&
                  random_u32(seed, STREAM_CRASH, r, 0, i) < crash_cut;
      if (want) {
        ++rank;
        if (max_crashed > 0 && still_cnt + rank > max_crashed) want = false;
      }
      down[i] = still || want;
      up[i] = !down[i];
    }
  }
};

// ---------------------------------------------------------------------------
// Raft (SPEC §3).
// ---------------------------------------------------------------------------

struct RaftSim {
  uint64_t seed;
  uint32_t N, R, L, E, t_min, t_max;
  uint32_t drop_cut, part_cut, churn_cut;
  uint32_t A = 0;  // max_active: 0 = dense (SPEC §3), >0 = capped (SPEC §3b)
  // SPEC §3c byzantine minority (ids >= N - n_byz): byz_equiv = 0 ->
  // "silent" (withhold every send), 1 -> "equivocate" (double-grant).
  uint32_t n_byz = 0, byz_equiv = 0;
  uint32_t delivery = DELIVERY_AUTO;
  // SPEC §6c / §A.2 adversary knobs (0 = off).
  uint32_t crash_cut = 0, recover_cut = 0, max_crashed = 0, max_delay = 0;
  CrashAdv crash;
  // SPEC §9 switch model (vote responses via K aggregators).
  uint32_t net_switch = 0, n_agg = 0;
  uint32_t agg_fail_cut = 0, agg_stale_cut = 0, agg_max_stale = 1;
  AggNet agg;

  // Auto: the capped round queries only O(A·N) edges — edge-wise makes
  // it tractable at 100k nodes; the dense round touches ~every edge ~7
  // times, so the materialized matrix stays the better baseline there.
  bool edge_net() const {
    if (delivery == DELIVERY_AUTO) return A > 0;
    return delivery == DELIVERY_EDGE;
  }

  // The SPEC §9 vote-response leg j → c: the flat §2 edge in the
  // historic model, the factorized two-hop through j's aggregator
  // under net_model="switch" (phase 0 = election vote responses).
  // Receiver liveness is the caller's guard (P2c skips down tallies).
  bool vote_leg(uint32_t j, uint32_t c) const {
    if (!net_switch) return net.delivered(j, c);
    if (crash.on && !crash.up[j]) return false;
    return agg.two_hop(0, j, c);
  }

  // State, struct-of-arrays to mirror the array schema (SURVEY.md §7).
  std::vector<uint32_t> term, role, log_len, commit, timer, timeout;
  std::vector<int32_t> voted_for;
  std::vector<uint32_t> log_term, log_val;        // [N*L]
  std::vector<uint32_t> match_idx, next_idx;      // [N*N] (dense only)
  // Tracked-leader slots (capped engine only — SPEC §3b).
  std::vector<int32_t> lead_id;                   // [A]
  std::vector<uint32_t> lead_match, lead_next;    // [A*N]
  Net net;

  uint32_t& lt(uint32_t i, uint32_t k) { return log_term[i * L + k]; }
  uint32_t& lv(uint32_t i, uint32_t k) { return log_val[i * L + k]; }
  uint32_t& mi(uint32_t l, uint32_t j) { return match_idx[l * N + j]; }
  uint32_t& ni(uint32_t l, uint32_t j) { return next_idx[l * N + j]; }

  uint32_t draw_timeout(uint32_t t, uint32_t i) const {
    return t_min + random_u32(seed, STREAM_TIMEOUT, t, 0, i) % (t_max - t_min);
  }

  bool honest(uint32_t i) const { return i < N - n_byz; }
  bool withhold() const { return n_byz > 0 && byz_equiv == 0; }
  bool dbl_grant() const { return n_byz > 0 && byz_equiv == 1; }

  // SPEC §3 term-change rule (non-candidacy causes).
  void bump_term(uint32_t i, uint32_t T) {
    term[i] = T;
    role[i] = ROLE_F;
    voted_for[i] = NONE;
    timeout[i] = draw_timeout(T, i);
  }

  void init() {
    term.assign(N, 0); role.assign(N, ROLE_F); log_len.assign(N, 0);
    commit.assign(N, 0); timer.assign(N, 0); voted_for.assign(N, NONE);
    timeout.resize(N);
    log_term.assign(size_t(N) * L, 0); log_val.assign(size_t(N) * L, 0);
    if (A == 0) {
      match_idx.assign(size_t(N) * N, 0); next_idx.assign(size_t(N) * N, 1);
    } else {
      lead_id.assign(A, NONE);
      lead_match.assign(size_t(A) * N, 0);
      lead_next.assign(size_t(A) * N, 1);
    }
    for (uint32_t i = 0; i < N; ++i) timeout[i] = draw_timeout(0, i);
    crash.init(N, crash_cut);
  }

  // SPEC §6c round prologue shared by both rounds: advance the down
  // mask, apply the volatile reset on recovery (role/timer; the dense
  // engine also re-inits the recovering node's leader bookkeeping rows
  // — the capped engine's tracked-slot lifecycle re-inits on entry).
  // Down nodes' delivery is killed via Net's up mask; every local
  // state mutation below is guarded on up, which together equal the
  // JAX engines' freeze (a down node's state can only move through
  // those local steps once its edges are dead).
  void crash_prologue(uint32_t r) {
    crash.advance(seed, r, crash_cut, recover_cut, max_crashed);
    if (!crash.on) return;
    for (uint32_t i = 0; i < N; ++i)
      if (crash.rec[i]) {
        role[i] = ROLE_F;
        timer[i] = 0;
        if (A == 0)
          for (uint32_t j = 0; j < N; ++j) { mi(i, j) = 0; ni(i, j) = 1; }
      }
  }

  // SPEC §3b active set: ids of the top-A ``mask`` nodes by
  // (term desc, id asc), NONE-padded to length A.
  std::vector<int32_t> top_active(const std::vector<uint8_t>& mask) const {
    std::vector<int32_t> ids;
    for (uint32_t i = 0; i < N; ++i)
      if (mask[i]) ids.push_back(int32_t(i));
    std::sort(ids.begin(), ids.end(), [&](int32_t a, int32_t b) {
      if (term[a] != term[b]) return term[a] > term[b];
      return a < b;
    });
    ids.resize(std::min<size_t>(ids.size(), A));
    ids.resize(A, NONE);
    return ids;
  }

  void round(uint32_t r) {
    const uint32_t majority = N / 2 + 1;
    crash_prologue(r);
    net.begin_round(seed, N, r, drop_cut, part_cut, edge_net(), max_delay,
                    crash.up_mask());
    if (net_switch)
      agg.begin_round(seed, N, n_agg, r, drop_cut, part_cut, max_delay,
                      agg_fail_cut, agg_stale_cut, agg_max_stale);
    std::vector<uint8_t> reset(N, 0);

    // ---- P0 churn: all leaders step down.
    if (churn_fires(seed, r, churn_cut))
      for (uint32_t i = 0; i < N; ++i)
        if (!crash.is_down(i) && role[i] == ROLE_L) {
          role[i] = ROLE_F; timer[i] = 0; reset[i] = 1;
        }

    // ---- P1 candidacy.
    for (uint32_t i = 0; i < N; ++i)
      if (!crash.is_down(i) && role[i] != ROLE_L && timer[i] >= timeout[i]) {
        term[i] += 1;
        role[i] = ROLE_C;
        voted_for[i] = int32_t(i);
        timer[i] = 0; reset[i] = 1;
        timeout[i] = draw_timeout(term[i], i);
      }

    // ---- P2 election. Snapshot requests (post-P1 sender state).
    std::vector<uint8_t> was_cand(N);
    std::vector<uint32_t> req_term(N), req_lidx(N), req_lterm(N);
    for (uint32_t c = 0; c < N; ++c) {
      was_cand[c] = role[c] == ROLE_C &&
                    (!withhold() || honest(c));  // SPEC §3c silent byz
      req_term[c] = term[c];
      req_lidx[c] = log_len[c];
      req_lterm[c] = log_len[c] ? lt(c, log_len[c] - 1) : 0;
    }
    // P2a: term catch-up from delivered requests.
    for (uint32_t j = 0; j < N; ++j) {
      uint32_t T = term[j];
      for (uint32_t c = 0; c < N; ++c)
        if (was_cand[c] && net.delivered(c, j)) T = std::max(T, req_term[c]);
      if (T > term[j]) bump_term(j, T);
    }
    // P2b: grants.
    std::vector<int32_t> grant(N, NONE);
    for (uint32_t j = 0; j < N; ++j) {
      uint32_t own_lterm = log_len[j] ? lt(j, log_len[j] - 1) : 0;
      int32_t g = NONE;
      auto eligible = [&](uint32_t c) {
        if (!was_cand[c] || c == j || !net.delivered(c, j)) return false;
        if (req_term[c] != term[j]) return false;
        return req_lterm[c] > own_lterm ||
               (req_lterm[c] == own_lterm && req_lidx[c] >= log_len[j]);
      };
      if (voted_for[j] != NONE) {
        if (eligible(uint32_t(voted_for[j]))) g = voted_for[j];  // re-grant
      } else {
        for (uint32_t c = 0; c < N; ++c)
          if (eligible(c)) { g = int32_t(c); break; }  // lowest id
      }
      if (g != NONE) { voted_for[j] = g; timer[j] = 0; reset[j] = 1; }
      grant[j] = g;
    }
    // P2c: tally; winners become leaders.
    for (uint32_t c = 0; c < N; ++c) {
      if (crash.is_down(c)) continue;   // SPEC §6c: frozen while down
      if (role[c] != ROLE_C) continue;  // may have been bumped in P2a
      uint32_t votes = 1;  // self
      for (uint32_t j = 0; j < N; ++j) {
        if (j == c) continue;
        if (dbl_grant() && !honest(j)) {
          // SPEC §3c equivocate: byz j responds to EVERY delivered
          // candidate request, ignoring term/up-to-date checks (the
          // request leg stays flat; the response rides vote_leg, §9).
          if (was_cand[c] && net.delivered(c, j) && vote_leg(j, c))
            ++votes;
        } else if ((!withhold() || honest(j)) && grant[j] == int32_t(c) &&
                   vote_leg(j, c)) {
          ++votes;
        }
      }
      if (votes >= majority) {
        role[c] = ROLE_L;
        timer[c] = 0; reset[c] = 1;
        for (uint32_t j = 0; j < N; ++j) { mi(c, j) = 0; ni(c, j) = log_len[c] + 1; }
        mi(c, c) = log_len[c];
      }
    }

    // ---- P3 replication.
    // (a) propose.
    for (uint32_t l = 0; l < N; ++l)
      if (!crash.is_down(l) && role[l] == ROLE_L && log_len[l] < E &&
          log_len[l] < L) {
        lt(l, log_len[l]) = term[l];
        lv(l, log_len[l]) = random_u32(seed, STREAM_VALUE, r, 0, l);
        log_len[l] += 1;
        mi(l, l) = log_len[l];
      }
    // (b) snapshot sender state (post-(a), commit pre-(e)).
    std::vector<uint8_t> was_leader(N);
    std::vector<uint32_t> s_term(N), s_len(N), s_commit(N);
    std::vector<uint32_t> s_next;  // [N*N] snapshot of next_idx
    s_next = next_idx;
    std::vector<uint32_t> s_logt = log_term, s_logv = log_val;
    for (uint32_t l = 0; l < N; ++l) {
      was_leader[l] = role[l] == ROLE_L &&
                      (!withhold() || honest(l));  // SPEC §3c silent byz
      s_term[l] = term[l]; s_len[l] = log_len[l]; s_commit[l] = commit[l];
    }
    // (c) receivers.
    std::vector<int32_t> ack_to(N, NONE);
    std::vector<uint8_t> ack_ok(N, 0);
    std::vector<uint32_t> ack_match(N, 0), ack_term(N, 0);
    for (uint32_t j = 0; j < N; ++j) {
      if (crash.is_down(j)) continue;  // SPEC §6c: frozen while down
      uint32_t T = term[j];
      for (uint32_t l = 0; l < N; ++l)
        if (was_leader[l] && net.delivered(l, j)) T = std::max(T, s_term[l]);
      if (T > term[j]) bump_term(j, T);
      int32_t lstar = NONE;
      for (uint32_t l = 0; l < N; ++l)
        if (was_leader[l] && l != j && net.delivered(l, j) && s_term[l] == term[j]) {
          lstar = int32_t(l);
          break;  // lowest id
        }
      if (lstar == NONE) continue;
      uint32_t l = uint32_t(lstar);
      timer[j] = 0; reset[j] = 1;
      if (role[j] == ROLE_C) role[j] = ROLE_F;
      uint32_t prev = s_next[l * N + j] - 1;
      uint32_t prev_term = prev ? s_logt[size_t(l) * L + prev - 1] : 0;
      bool ok = prev == 0 ||
                (prev <= log_len[j] && lt(j, prev - 1) == prev_term);
      ack_to[j] = lstar;
      ack_term[j] = term[j];
      if (ok) {
        for (uint32_t k = prev; k < s_len[l]; ++k) {
          lt(j, k) = s_logt[size_t(l) * L + k];
          lv(j, k) = s_logv[size_t(l) * L + k];
        }
        log_len[j] = s_len[l];
        commit[j] = std::max(commit[j], std::min(s_commit[l], log_len[j]));
        ack_ok[j] = 1;
        ack_match[j] = s_len[l];
      }
    }
    // (d) leaders process acks (only if still leader after (c)).
    for (uint32_t l = 0; l < N; ++l) {
      if (crash.is_down(l)) continue;  // SPEC §6c: frozen while down
      if (!was_leader[l] || role[l] != ROLE_L) continue;
      uint32_t T = term[l];
      for (uint32_t j = 0; j < N; ++j)
        if (ack_to[j] == int32_t(l) && net.delivered(j, l) &&
            (!withhold() || honest(j)))
          T = std::max(T, ack_term[j]);
      if (T > term[l]) { bump_term(l, T); continue; }
      for (uint32_t j = 0; j < N; ++j) {
        if (ack_to[j] != int32_t(l) || !net.delivered(j, l)) continue;
        if (withhold() && !honest(j)) continue;  // byz acks never travel
        if (ack_ok[j]) {
          mi(l, j) = std::max(mi(l, j), ack_match[j]);
          ni(l, j) = mi(l, j) + 1;
        } else {
          ni(l, j) = std::max(1u, ni(l, j) - 1);
        }
      }
      // (e) commit advance.
      std::vector<uint32_t> m(match_idx.begin() + size_t(l) * N,
                              match_idx.begin() + size_t(l) * N + N);
      std::nth_element(m.begin(), m.begin() + (majority - 1), m.end(),
                       std::greater<uint32_t>());
      uint32_t med = m[majority - 1];
      if (med > commit[l] && med > 0 && lt(l, med - 1) == term[l])
        commit[l] = med;
    }

    // ---- P4 timers.
    for (uint32_t i = 0; i < N; ++i) {
      if (crash.is_down(i)) continue;  // SPEC §6c: frozen while down
      if (role[i] == ROLE_L) timer[i] = 0;
      else if (!reset[i]) timer[i] += 1;
    }
  }

  // One SPEC §3b round: identical phase structure to `round`, but only
  // the top-A candidates / top-A tracked leaders send, and replication
  // bookkeeping lives in A tracked [A, N] rows instead of [N, N].
  // Scalar twin of engines/raft_sparse.py (decided logs bit-equal to the
  // dense semantics whenever concurrent sender counts stay <= A).
  //
  // O(A·N) per round end to end: delivery is queried edge-wise (under
  // the default auto mode) and every per-receiver loop below iterates
  // the ≤A active sender ids, never the population — the two
  // together are what let the oracle run the 100k-node flagship config
  // in seconds instead of materializing ~10¹⁰ matrix cells
  // (docs/PERF.md "oracle asymptotics").
  void round_capped(uint32_t r) {
    const uint32_t majority = N / 2 + 1;
    crash_prologue(r);
    net.begin_round(seed, N, r, drop_cut, part_cut, edge_net(), max_delay,
                    crash.up_mask());
    if (net_switch)
      agg.begin_round(seed, N, n_agg, r, drop_cut, part_cut, max_delay,
                      agg_fail_cut, agg_stale_cut, agg_max_stale);
    std::vector<uint8_t> reset(N, 0);

    // ---- P0 churn.
    if (churn_fires(seed, r, churn_cut))
      for (uint32_t i = 0; i < N; ++i)
        if (!crash.is_down(i) && role[i] == ROLE_L) {
          role[i] = ROLE_F; timer[i] = 0; reset[i] = 1;
        }

    // ---- P1 candidacy.
    for (uint32_t i = 0; i < N; ++i)
      if (!crash.is_down(i) && role[i] != ROLE_L && timer[i] >= timeout[i]) {
        term[i] += 1;
        role[i] = ROLE_C;
        voted_for[i] = int32_t(i);
        timer[i] = 0; reset[i] = 1;
        timeout[i] = draw_timeout(term[i], i);
      }

    // ---- P2 election over the active candidate set (down candidates
    // are untracked — SPEC §6c: they send nothing).
    std::vector<uint8_t> is_cand(N);
    for (uint32_t i = 0; i < N; ++i)
      is_cand[i] = role[i] == ROLE_C && !crash.is_down(i) &&
                   (!withhold() || honest(i));  // SPEC §3c silent byz
    const std::vector<int32_t> cand_ids = top_active(is_cand);
    std::vector<uint8_t> active_cand(N, 0);
    // The active ids again, ascending — the ONLY senders the P2a/P2b
    // receiver loops may visit (an O(N) scan per receiver here was the
    // residual O(N²) term after delivery went edge-wise); ascending
    // order preserves the lowest-id-first grant tie-break verbatim.
    std::vector<uint32_t> act_asc;
    act_asc.reserve(A);
    for (int32_t c : cand_ids)
      if (c >= 0) { active_cand[c] = 1; act_asc.push_back(uint32_t(c)); }
    std::sort(act_asc.begin(), act_asc.end());
    std::vector<uint32_t> req_term(N, 0), req_lidx(N, 0), req_lterm(N, 0);
    for (uint32_t c : act_asc) {
      req_term[c] = term[c];
      req_lidx[c] = log_len[c];
      req_lterm[c] = log_len[c] ? lt(c, log_len[c] - 1) : 0;
    }
    // P2a: term catch-up from delivered active requests.
    for (uint32_t j = 0; j < N; ++j) {
      uint32_t T = term[j];
      for (uint32_t c : act_asc)
        if (net.delivered(c, j)) T = std::max(T, req_term[c]);
      if (T > term[j]) bump_term(j, T);
    }
    // P2b: grants (eligibility restricted to active candidates).
    std::vector<int32_t> grant(N, NONE);
    for (uint32_t j = 0; j < N; ++j) {
      uint32_t own_lterm = log_len[j] ? lt(j, log_len[j] - 1) : 0;
      int32_t g = NONE;
      auto eligible = [&](uint32_t c) {
        if (!active_cand[c] || c == j || !net.delivered(c, j)) return false;
        if (req_term[c] != term[j]) return false;
        return req_lterm[c] > own_lterm ||
               (req_lterm[c] == own_lterm && req_lidx[c] >= log_len[j]);
      };
      if (voted_for[j] != NONE) {
        if (eligible(uint32_t(voted_for[j]))) g = voted_for[j];  // re-grant
      } else {
        for (uint32_t c : act_asc)
          if (eligible(c)) { g = int32_t(c); break; }  // lowest id
      }
      if (g != NONE) { voted_for[j] = g; timer[j] = 0; reset[j] = 1; }
      grant[j] = g;
    }
    // P2c: tally per active candidate; winners become leaders (tracked
    // rows are assigned by the slot lifecycle below, not here).
    for (int32_t ci : cand_ids) {
      if (ci < 0) continue;
      uint32_t c = uint32_t(ci);
      if (role[c] != ROLE_C) continue;  // may have been bumped in P2a
      uint32_t votes = 1;  // self
      for (uint32_t j = 0; j < N; ++j) {
        if (j == c) continue;
        if (dbl_grant() && !honest(j)) {
          // SPEC §3c equivocate: byz j responds to EVERY delivered
          // active candidate request (response via vote_leg — SPEC §9).
          if (net.delivered(c, j) && vote_leg(j, c)) ++votes;
        } else if ((!withhold() || honest(j)) && grant[j] == int32_t(c) &&
                   vote_leg(j, c)) {
          ++votes;
        }
      }
      if (votes >= majority) { role[c] = ROLE_L; timer[c] = 0; reset[c] = 1; }
    }

    // ---- Tracked-leader slot lifecycle: rows follow ids; entries and
    // re-entries get fresh election-time rows (match 0 except self,
    // next = log_len + 1 — log_len BEFORE this round's P3a append).
    // Down leaders are untracked (SPEC §6c: they replicate nothing).
    std::vector<uint8_t> is_lead(N);
    for (uint32_t i = 0; i < N; ++i)
      is_lead[i] = role[i] == ROLE_L && !crash.is_down(i);
    const std::vector<int32_t> new_ids = top_active(is_lead);
    std::vector<uint32_t> nmatch(size_t(A) * N, 0), nnext(size_t(A) * N, 1);
    for (uint32_t k = 0; k < A; ++k) {
      const int32_t id = new_ids[k];
      if (id < 0) continue;
      int32_t src = NONE;
      for (uint32_t s = 0; s < A; ++s)
        if (lead_id[s] == id) { src = int32_t(s); break; }
      if (src >= 0) {
        std::copy_n(lead_match.begin() + size_t(src) * N, N,
                    nmatch.begin() + size_t(k) * N);
        std::copy_n(lead_next.begin() + size_t(src) * N, N,
                    nnext.begin() + size_t(k) * N);
      } else {
        nmatch[size_t(k) * N + id] = log_len[id];
        std::fill_n(nnext.begin() + size_t(k) * N, N, log_len[id] + 1);
      }
    }
    lead_match.swap(nmatch);
    lead_next.swap(nnext);
    lead_id = new_ids;

    // ---- P3a propose: every leader appends locally (tracked or not);
    // tracked leaders' self-match follows their own append.
    for (uint32_t l = 0; l < N; ++l)
      if (!crash.is_down(l) && role[l] == ROLE_L && log_len[l] < E &&
          log_len[l] < L) {
        lt(l, log_len[l]) = term[l];
        lv(l, log_len[l]) = random_u32(seed, STREAM_VALUE, r, 0, l);
        log_len[l] += 1;
      }
    for (uint32_t k = 0; k < A; ++k)
      if (lead_id[k] >= 0 && role[lead_id[k]] == ROLE_L)
        lead_match[size_t(k) * N + lead_id[k]] = log_len[lead_id[k]];

    // ---- P3b snapshot tracked-sender state (post-(a), commit pre-(e)).
    std::vector<uint8_t> was_lead_k(A, 0);
    std::vector<uint32_t> s_term(A, 0), s_len(A, 0), s_commit(A, 0);
    const std::vector<uint32_t> s_next = lead_next;
    const std::vector<uint32_t> s_logt = log_term, s_logv = log_val;
    for (uint32_t k = 0; k < A; ++k) {
      if (lead_id[k] < 0) continue;
      const uint32_t l = uint32_t(lead_id[k]);
      was_lead_k[k] = role[l] == ROLE_L &&
                      (!withhold() || honest(l));  // SPEC §3c silent byz
      s_term[k] = term[l]; s_len[k] = log_len[l]; s_commit[k] = commit[l];
    }

    // ---- P3c receivers (senders = tracked leading slots only).
    std::vector<int32_t> ack_slot(N, NONE);
    std::vector<uint8_t> ack_ok(N, 0);
    std::vector<uint32_t> ack_match(N, 0), ack_term(N, 0);
    for (uint32_t j = 0; j < N; ++j) {
      if (crash.is_down(j)) continue;  // SPEC §6c: frozen while down
      uint32_t T = term[j];
      for (uint32_t k = 0; k < A; ++k)
        if (was_lead_k[k] && net.delivered(uint32_t(lead_id[k]), j))
          T = std::max(T, s_term[k]);
      if (T > term[j]) bump_term(j, T);
      int32_t kstar = NONE;
      uint32_t lstar = N;
      for (uint32_t k = 0; k < A; ++k) {
        if (!was_lead_k[k]) continue;
        const uint32_t l = uint32_t(lead_id[k]);
        if (l == j || !net.delivered(l, j) || s_term[k] != term[j]) continue;
        if (l < lstar) { lstar = l; kstar = int32_t(k); }  // lowest node id
      }
      if (kstar == NONE) continue;
      const uint32_t k = uint32_t(kstar), l = lstar;
      timer[j] = 0; reset[j] = 1;
      if (role[j] == ROLE_C) role[j] = ROLE_F;
      const uint32_t prev = s_next[size_t(k) * N + j] - 1;
      const uint32_t prev_term = prev ? s_logt[size_t(l) * L + prev - 1] : 0;
      const bool ok = prev == 0 ||
                      (prev <= log_len[j] && lt(j, prev - 1) == prev_term);
      ack_slot[j] = kstar;
      ack_term[j] = term[j];
      if (ok) {
        for (uint32_t x = prev; x < s_len[k]; ++x) {
          lt(j, x) = s_logt[size_t(l) * L + x];
          lv(j, x) = s_logv[size_t(l) * L + x];
        }
        log_len[j] = s_len[k];
        commit[j] = std::max(commit[j], std::min(s_commit[k], log_len[j]));
        ack_ok[j] = 1;
        ack_match[j] = s_len[k];
      }
    }

    // ---- P3d tracked leaders process acks; P3e commit advance.
    for (uint32_t k = 0; k < A; ++k) {
      if (!was_lead_k[k]) continue;
      const uint32_t l = uint32_t(lead_id[k]);
      if (role[l] != ROLE_L) continue;
      uint32_t T = term[l];
      for (uint32_t j = 0; j < N; ++j)
        if (ack_slot[j] == int32_t(k) && net.delivered(j, l) &&
            (!withhold() || honest(j)))
          T = std::max(T, ack_term[j]);
      if (T > term[l]) { bump_term(l, T); continue; }
      for (uint32_t j = 0; j < N; ++j) {
        if (ack_slot[j] != int32_t(k) || !net.delivered(j, l)) continue;
        if (withhold() && !honest(j)) continue;  // byz acks never travel
        uint32_t& m = lead_match[size_t(k) * N + j];
        uint32_t& nx = lead_next[size_t(k) * N + j];
        if (ack_ok[j]) {
          m = std::max(m, ack_match[j]);
          nx = m + 1;
        } else {
          nx = std::max(1u, nx - 1);
        }
      }
      std::vector<uint32_t> m(lead_match.begin() + size_t(k) * N,
                              lead_match.begin() + size_t(k) * N + N);
      std::nth_element(m.begin(), m.begin() + (majority - 1), m.end(),
                       std::greater<uint32_t>());
      const uint32_t med = m[majority - 1];
      if (med > commit[l] && med > 0 && lt(l, med - 1) == term[l])
        commit[l] = med;
    }

    // ---- P4 timers.
    for (uint32_t i = 0; i < N; ++i) {
      if (crash.is_down(i)) continue;  // SPEC §6c: frozen while down
      if (role[i] == ROLE_L) timer[i] = 0;
      else if (!reset[i]) timer[i] += 1;
    }
  }

  void run() {
    init();
    if (A == 0)
      for (uint32_t r = 0; r < R; ++r) round(r);
    else
      for (uint32_t r = 0; r < R; ++r) round_capped(r);
  }
};

// ---------------------------------------------------------------------------
// PBFT (SPEC §6).
// ---------------------------------------------------------------------------

struct PbftSim {
  uint64_t seed;
  uint32_t N, R, S, f, view_timeout, n_byz;
  uint32_t equiv = 0;        // byz_mode == "equivocate" (SPEC §6)
  uint32_t fault_bcast = 0;  // SPEC §6b broadcast-atomic fault model
  uint32_t drop_cut, part_cut, churn_cut;
  uint32_t delivery = DELIVERY_AUTO;
  // SPEC §6c / §A.2 adversary knobs (0 = off).
  uint32_t crash_cut = 0, recover_cut = 0, max_crashed = 0, max_delay = 0;
  CrashAdv crash;
  // SPEC §9 switch model: P4/P5 vote tallies + P6 decide gossip via K
  // aggregators (phases 0/1/2); P1 view sync and the P3 pre-prepare
  // stay flat (control plane / one-sender traffic).
  uint32_t net_switch = 0, n_agg = 0;
  uint32_t agg_fail_cut = 0, agg_stale_cut = 0, agg_max_stale = 1;
  AggNet agg;
  // SPEC §B view-synchronizer timer skew (0 = off).
  uint32_t desync_cut = 0, max_skew = 1;

  // The §6 dense tallies walk ~every (i, j) pair anyway, so the
  // materialized Net stays the auto choice for the edge fault model;
  // forcing DELIVERY_EDGE is the small-N cross-check knob.
  bool edge_net() const { return delivery == DELIVERY_EDGE; }
  // §6b only: under broadcast-atomic faults every per-receiver multiset
  // is side-separable, so P1/P4/P5/P6 reduce to per-(slot, side)
  // aggregates — O(N·S) per round instead of O(N²·S), which is what
  // lets the oracle run pbft-100k-bcast at its benchmark shape.
  // DELIVERY_DENSE forces the direct per-receiver §6b definition — kept
  // alive as an independent derivation the differential tests
  // cross-check against both this fast path and the engine's
  // sorted-space formulation.
  bool bcast_fast() const { return fault_bcast && delivery != DELIVERY_DENSE; }

  std::vector<uint32_t> view, timer;                    // [N]
  std::vector<uint8_t> pp_seen, prepared, committed;    // [N*S]
  std::vector<uint32_t> pp_view, pp_val, dval;          // [N*S]
  Net net;

  size_t at(uint32_t n, uint32_t s) const { return size_t(n) * S + s; }
  bool honest(uint32_t i) const { return i < N - n_byz; }
  // Byz i's per-receiver stance in round r (SPEC §6 equivocate mode).
  bool sup(uint32_t r, uint32_t i, uint32_t j) const {
    return random_u32(seed, STREAM_EQUIV, r, i, j) & 1u;
  }

  // --- SPEC §6b (fault_bcast): broadcast-atomic delivery -------------
  // Scalar twin of engines/pbft_bcast.py, implemented straight from the
  // §6b definition (per-receiver multisets), NOT via the engine's
  // sorted-count formulation — so the differential tests cross-check
  // two independent derivations.
  struct BcastNet {
    uint64_t seed;
    uint32_t r = 0;
    bool part_active = false;
    std::vector<uint8_t> bcast, side;  // [N]

    void begin_round(uint64_t seed_, uint32_t n, uint32_t r_,
                     uint32_t drop_cut, uint32_t part_cut,
                     uint32_t max_delay = 0, const uint8_t* up = nullptr) {
      seed = seed_;
      r = r_;
      bcast.resize(n);
      side.assign(n, 0);
      part_active = random_u32(seed, STREAM_PARTITION, r, 0, 0) < part_cut;
      for (uint32_t i = 0; i < n; ++i) {
        // SPEC §A.2 delayed retransmission on the broadcast key (i, i);
        // SPEC §6c folds a down sender's broadcast drop in atomically.
        bool b = delivery_u32(seed, r, i, i) >= drop_cut;
        if (!b && max_delay)
          b = delayed_open(seed, r, i, i, drop_cut, max_delay);
        bcast[i] = b && (!up || up[i]);
        if (part_active)
          side[i] = random_u32(seed, STREAM_PARTITION, r, 1, i) & 1u;
      }
    }
    // i's round broadcast reaches j (i != j handled by callers).
    bool delivered(uint32_t i, uint32_t j) const {
      return bcast[i] && (!part_active || side[i] == side[j]);
    }
  };
  BcastNet bnet;

  // Byz i's per-ROUND stance (SPEC §9: the switch dedups per-receiver
  // claims, so ONLY the aggregated round uses it; both flat fault
  // models equivocate per receiver via sup(r, i, j) — SPEC §7c).
  bool stance(uint32_t r, uint32_t i) const {
    return random_u32(seed, STREAM_EQUIV, r, i, 0x80000000u) & 1u;
  }
  // Fault-model-dispatched delivery.
  bool del(uint32_t /*r*/, uint32_t i, uint32_t j) const {
    return fault_bcast ? bnet.delivered(i, j) : net.delivered(i, j);
  }

  void run() {
    view.assign(N, 0); timer.assign(N, 0);
    pp_seen.assign(size_t(N) * S, 0); prepared.assign(size_t(N) * S, 0);
    committed.assign(size_t(N) * S, 0);
    pp_view.assign(size_t(N) * S, 0); pp_val.assign(size_t(N) * S, 0);
    dval.assign(size_t(N) * S, 0);
    crash.init(N, crash_cut);
    for (uint32_t r = 0; r < R; ++r) {
      // SPEC §6c prologue: advance the down mask, volatile reset on
      // recovery (view/timer rejoin at 0; the per-slot message log is
      // the persisted state PBFT's safety argument rests on). Down
      // nodes neither send (Net up mask / folded bcast) nor mutate
      // local state (per-receiver guards in the rounds below).
      crash.advance(seed, r, crash_cut, recover_cut, max_crashed);
      if (crash.on)
        for (uint32_t i = 0; i < N; ++i)
          if (crash.rec[i]) { view[i] = 0; timer[i] = 0; }
      // SPEC §B timer-skew injection (engines/pbft.py placement: after
      // the volatile reset, before churn): an up node's local timer
      // jumps ahead so P2's start-of-round timeout fires prematurely.
      // Down nodes draw nothing — the JAX freeze discards their skew.
      if (desync_cut)
        for (uint32_t i = 0; i < N; ++i) {
          if (crash.is_down(i)) continue;
          if (random_u32(seed, STREAM_DESYNC, r, 0, i) < desync_cut)
            timer[i] +=
                1 + random_u32(seed, STREAM_DESYNC, r, 1, i) % max_skew;
        }
      if (fault_bcast)
        bnet.begin_round(seed, N, r, drop_cut, part_cut, max_delay,
                         crash.up_mask());
      else
        net.begin_round(seed, N, r, drop_cut, part_cut, edge_net(),
                        max_delay, crash.up_mask());
      if (net_switch) {
        agg.begin_round(seed, N, n_agg, r, drop_cut, part_cut, max_delay,
                        agg_fail_cut, agg_stale_cut, agg_max_stale);
        round_switch(r);
      } else if (bcast_fast()) {
        round_bcast_fast(r);
      } else {
        round_direct(r);
      }
    }
  }

  // P3 pre-prepare — shared verbatim by the direct and aggregate rounds
  // (one sender per receiver, O(N·S); delivery and equivocation stance
  // dispatch through del()/eq_sup()). Snapshot sender state post-P2.
  void phase_preprepare(uint32_t r) {
    const std::vector<uint32_t> s_view = view;
    std::vector<uint8_t> s_ppb(size_t(N) * S, 0);    // pre-prepare bcast set
    std::vector<uint32_t> s_msgval(size_t(N) * S, 0);
    for (uint32_t i = 0; i < N; ++i) {
      if (!honest(i) || s_view[i] % N != i) continue;
      uint32_t fresh = S;
      for (uint32_t s = 0; s < S; ++s)
        if (!pp_seen[at(i, s)]) { fresh = s; break; }
      for (uint32_t s = 0; s < S; ++s) {
        bool reissue = pp_seen[at(i, s)] && !committed[at(i, s)];
        if (reissue || s == fresh) {
          s_ppb[at(i, s)] = 1;
          s_msgval[at(i, s)] = pp_seen[at(i, s)]
              ? pp_val[at(i, s)]
              : random_u32(seed, STREAM_VALUE, s_view[i], 2, s);
        }
      }
    }
    for (uint32_t j = 0; j < N; ++j) {
      if (crash.is_down(j)) continue;  // SPEC §6c: frozen while down
      uint32_t prim = view[j] % N;
      bool prim_byz = equiv && !honest(prim);
      bool pdel = prim == j || del(r, prim, j);
      // A byz primary lies about its view, so only delivery gates it;
      // it offers EVERY slot, per-receiver conflicting values.
      bool ok = prim_byz ? pdel : (pdel && s_view[prim] == view[j]);
      if (!ok) continue;
      for (uint32_t s = 0; s < S; ++s) {
        uint32_t v;
        if (prim_byz) {
          v = random_u32(seed, STREAM_VALUE, view[j],
                         sup(r, prim, j) ? 4 : 3, s);
        } else {
          if (!s_ppb[at(prim, s)]) continue;
          v = s_msgval[at(prim, s)];
        }
        if (pp_seen[at(j, s)] && pp_view[at(j, s)] >= view[j]) continue;
        if (prepared[at(j, s)] && v != pp_val[at(j, s)]) continue;
        pp_seen[at(j, s)] = 1;
        pp_view[at(j, s)] = view[j];
        pp_val[at(j, s)] = v;
      }
    }
  }

  // One SPEC §6 / §6b round straight from the per-receiver definition
  // (O(N²·S) tallies) — the small-N reference the aggregate §6b round
  // below (and the engines' formulations) are cross-checked against.
  void round_direct(uint32_t r) {
    const uint32_t Q = 2 * f + 1;
    std::vector<uint8_t> reset(N, 0), new_commit(N, 0);
    std::vector<uint32_t> views_in;  // for the f+1 rule
    std::vector<uint32_t> s_view(N);
    std::vector<uint8_t> s_seen, s_prep, s_comm;
    std::vector<uint32_t> s_val, s_dval;

    // P0 churn.
    if (churn_fires(seed, r, churn_cut))
      for (uint32_t i = 0; i < N; ++i) {
        if (crash.is_down(i)) continue;
        view[i] += 1; timer[i] = 0; reset[i] = 1;
      }

    // P1 view catch-up ((f+1)-th largest delivered honest view ∪ own).
    s_view = view;
    for (uint32_t j = 0; j < N; ++j) {
      if (crash.is_down(j)) continue;  // SPEC §6c: frozen while down
      views_in.clear();
      views_in.push_back(s_view[j]);
      for (uint32_t i = 0; i < N; ++i)
        if (i != j && honest(i) && del(r, i, j))
          views_in.push_back(s_view[i]);
      if (views_in.size() >= f + 1) {
        std::nth_element(views_in.begin(), views_in.begin() + f,
                         views_in.end(), std::greater<uint32_t>());
        uint32_t vth = views_in[f];
        if (vth > view[j]) { view[j] = vth; timer[j] = 0; reset[j] = 1; }
      }
    }

    // P2 timeout.
    for (uint32_t j = 0; j < N; ++j)
      if (!crash.is_down(j) && timer[j] >= view_timeout) {
        view[j] += 1; timer[j] = 0; reset[j] = 1;
      }

    // P3 pre-prepare (shared).
    phase_preprepare(r);

    // P4 prepare tally (value-matched, incl. self). Snapshot post-P3.
    s_seen = pp_seen; s_val = pp_val;
    for (uint32_t j = 0; j < N; ++j)
      for (uint32_t s = 0; s < S; ++s) {
        if (crash.is_down(j)) break;  // SPEC §6c: frozen while down
        if (!s_seen[at(j, s)] || prepared[at(j, s)]) continue;
        uint32_t cnt = 0;
        for (uint32_t i = 0; i < N; ++i) {
          if (honest(i) && s_seen[at(i, s)] &&
              s_val[at(i, s)] == s_val[at(j, s)] &&
              (i == j || del(r, i, j)))
            ++cnt;
          else if (equiv && !honest(i) && i != j && del(r, i, j) &&
                   sup(r, i, j))
            ++cnt;  // byz i claims j's exact value iff sup(r, i, j)
        }
        if (cnt >= Q) prepared[at(j, s)] = 1;
      }

    // P5 commit tally. Snapshot prepared post-P4.
    s_prep = prepared;
    for (uint32_t j = 0; j < N; ++j)
      for (uint32_t s = 0; s < S; ++s) {
        if (crash.is_down(j)) break;  // SPEC §6c: frozen while down
        if (!s_prep[at(j, s)] || committed[at(j, s)]) continue;
        uint32_t cnt = 0;
        for (uint32_t i = 0; i < N; ++i) {
          if (honest(i) && s_prep[at(i, s)] &&
              s_val[at(i, s)] == s_val[at(j, s)] &&
              (i == j || del(r, i, j)))
            ++cnt;
          else if (equiv && !honest(i) && i != j && del(r, i, j) &&
                   sup(r, i, j))
            ++cnt;
        }
        if (cnt >= Q) {
          committed[at(j, s)] = 1;
          dval[at(j, s)] = pp_val[at(j, s)];
          new_commit[j] = 1;
        }
      }

    // P6 decide gossip. Snapshot committed post-P5.
    s_comm = committed; s_dval = dval;
    for (uint32_t j = 0; j < N; ++j)
      for (uint32_t s = 0; s < S; ++s) {
        if (crash.is_down(j)) break;  // SPEC §6c: frozen while down
        if (s_comm[at(j, s)]) continue;
        for (uint32_t i = 0; i < N; ++i)  // ascending ⇒ lowest id wins
          if (honest(i) && s_comm[at(i, s)] && del(r, i, j)) {
            committed[at(j, s)] = 1;
            dval[at(j, s)] = s_dval[at(i, s)];
            new_commit[j] = 1;
            break;
          }
      }

    // P7 timer.
    for (uint32_t j = 0; j < N; ++j) {
      if (crash.is_down(j)) continue;  // SPEC §6c: frozen while down
      if (new_commit[j]) timer[j] = 0;
      else if (!reset[j]) timer[j] += 1;
    }
  }

  // One SPEC §6b round in per-(slot, side) aggregates — O(N·S·log N)
  // instead of the direct definition's O(N²·S). Under broadcast-atomic
  // faults a receiver's delivered-sender multiset depends only on its
  // partition side, so P1's order statistics, P4/P5's value-matched
  // tallies and P6's lowest-id decider all collapse to per-side
  // aggregates; per-round equivocation stances (SPEC §6b item 3) make
  // byz support value- and slot-independent. This is what lets the
  // oracle run pbft-100k-bcast at its benchmark shape (docs/PERF.md
  // "oracle asymptotics"); DELIVERY_DENSE forces round_direct, the
  // independent derivation the differential tests compare against.
  void round_bcast_fast(uint32_t r) {
    const uint32_t Q = 2 * f + 1, K = f + 1;
    const bool part = bnet.part_active;
    const uint32_t n_sides = part ? 2 : 1;
    auto side_of = [&](uint32_t i) -> uint32_t {
      return part ? bnet.side[i] : 0;
    };
    std::vector<uint8_t> reset(N, 0), new_commit(N, 0);

    // P0 churn.
    if (churn_fires(seed, r, churn_cut))
      for (uint32_t i = 0; i < N; ++i) {
        if (crash.is_down(i)) continue;
        view[i] += 1; timer[i] = 0; reset[i] = 1;
      }

    // P1 view catch-up. Per side: the K-th and (K-1)-th largest sender
    // views, -1-padded to K entries (views are >= 0, so the pads encode
    // the |views_in| >= f+1 rule). Receiver-side insertion is a clamp:
    // inserting own view x into a multiset whose K-th/(K-1)-th largest
    // are a1/a2 puts the new K-th largest at clip(x, a1, a2); a receiver
    // that IS a sender replaces its own copy, leaving the multiset
    // unchanged — so its vth is a1 directly.
    {
      std::vector<int64_t> vb[2];
      for (uint32_t i = 0; i < N; ++i)
        if (honest(i) && bnet.bcast[i])
          vb[side_of(i)].push_back(int64_t(view[i]));
      int64_t a1[2] = {0, 0}, a2[2] = {0, 0};
      for (uint32_t b = 0; b < n_sides; ++b) {
        std::vector<int64_t>& v = vb[b];
        while (v.size() < K) v.push_back(-1);
        std::partial_sort(v.begin(), v.begin() + K, v.end(),
                          std::greater<int64_t>());
        a1[b] = v[K - 1];
        a2[b] = K >= 2 ? v[K - 2] : std::numeric_limits<int64_t>::max();
      }
      for (uint32_t j = 0; j < N; ++j) {
        if (crash.is_down(j)) continue;  // SPEC §6c: frozen while down
        const uint32_t b = side_of(j);
        const int64_t x = int64_t(view[j]);
        const bool in_set = honest(j) && bnet.bcast[j];
        const int64_t vth =
            in_set ? a1[b] : std::min(std::max(x, a1[b]), a2[b]);
        if (vth > x) { view[j] = uint32_t(vth); timer[j] = 0; reset[j] = 1; }
      }
    }

    // P2 timeout.
    for (uint32_t j = 0; j < N; ++j)
      if (!crash.is_down(j) && timer[j] >= view_timeout) {
        view[j] += 1; timer[j] = 0; reset[j] = 1;
      }

    // P3 pre-prepare (shared).
    phase_preprepare(r);

    // Per-RECEIVER equivocation support (SPEC §7c): byz i's stance
    // toward receiver j is the dense kernel's sup(r, i, j) draw, with
    // the §6b atomic-broadcast fate, self-exclusion and the partition
    // filter folded — still value-independent, so one count per
    // receiver serves every slot. O(n_byz · N) once per round.
    std::vector<uint32_t> eq_cnt;
    if (equiv && n_byz > 0) {
      eq_cnt.assign(N, 0);
      for (uint32_t i = N - n_byz; i < N; ++i) {
        if (!bnet.bcast[i]) continue;
        for (uint32_t j = 0; j < N; ++j)
          if (i != j && (!part || bnet.side[i] == bnet.side[j]) &&
              sup(r, i, j))
            ++eq_cnt[j];
      }
    }

    // P4 + P5 per slot in value-sorted runs: every node rides one sort
    // of the slot's pp_val column, so a receiver's equal-value sender
    // class is exactly its run, and a per-(run, side) count of valid
    // broadcasting senders answers the tally for all receivers at once.
    // pp_val/pp_seen don't change during P4/P5, so both phases reuse
    // the one sort; P5's validity (prepared post-P4) is read after the
    // slot's P4 pass completes — slots are independent, matching the
    // direct round's whole-array snapshots.
    std::vector<uint32_t> ord(N), run_of(N), cnt;
    for (uint32_t s = 0; s < S; ++s) {
      for (uint32_t i = 0; i < N; ++i) ord[i] = i;
      std::sort(ord.begin(), ord.end(), [&](uint32_t a, uint32_t b) {
        return pp_val[at(a, s)] < pp_val[at(b, s)];
      });
      uint32_t nruns = 0;
      for (uint32_t k = 0; k < N; ++k) {
        if (k > 0 && pp_val[at(ord[k], s)] != pp_val[at(ord[k - 1], s)])
          ++nruns;
        run_of[ord[k]] = nruns;
      }
      ++nruns;
      const auto tally = [&](const std::vector<uint8_t>& relevant) {
        cnt.assign(size_t(nruns) * n_sides, 0);
        for (uint32_t i = 0; i < N; ++i)
          if (honest(i) && bnet.bcast[i] && relevant[at(i, s)])
            ++cnt[size_t(run_of[i]) * n_sides + side_of(i)];
      };
      const auto count_for = [&](uint32_t j) -> uint32_t {
        uint32_t c = cnt[size_t(run_of[j]) * n_sides + side_of(j)];
        if (honest(j) && !bnet.bcast[j]) ++c;  // self vote never travels
        if (equiv && n_byz > 0) c += eq_cnt[j];
        return c;
      };
      // P4 prepare tally (value-matched, incl. self). A down receiver
      // can neither prepare nor commit (SPEC §6c) — down SENDERS are
      // already outside every count via the folded bcast flag.
      tally(pp_seen);
      for (uint32_t j = 0; j < N; ++j) {
        if (crash.is_down(j)) continue;
        if (!pp_seen[at(j, s)] || prepared[at(j, s)]) continue;
        if (count_for(j) >= Q) prepared[at(j, s)] = 1;
      }
      // P5 commit tally over post-P4 prepared.
      tally(prepared);
      for (uint32_t j = 0; j < N; ++j) {
        if (crash.is_down(j)) continue;
        if (!prepared[at(j, s)] || committed[at(j, s)]) continue;
        if (count_for(j) >= Q) {
          committed[at(j, s)] = 1;
          dval[at(j, s)] = pp_val[at(j, s)];
          new_commit[j] = 1;
        }
      }
      // P6 decide gossip: lowest-id broadcasting honest decider per
      // (slot, side), fixed BEFORE any adoption (adopters are
      // uncommitted, so they can never be a decider this round).
      uint32_t imin[2] = {N, N};
      uint32_t unset = n_sides;  // early exit once every LIVE side is set
      for (uint32_t i = 0; i < N && unset; ++i) {
        if (!honest(i) || !bnet.bcast[i] || !committed[at(i, s)]) continue;
        const uint32_t b = side_of(i);
        if (imin[b] == N) { imin[b] = i; --unset; }  // ascending ⇒ lowest id
      }
      for (uint32_t j = 0; j < N; ++j) {
        if (crash.is_down(j)) continue;  // down receivers adopt nothing
        if (committed[at(j, s)]) continue;
        const uint32_t b = side_of(j);
        if (imin[b] == N) continue;
        committed[at(j, s)] = 1;
        dval[at(j, s)] = dval[at(imin[b], s)];
        new_commit[j] = 1;
      }
    }

    // P7 timer.
    for (uint32_t j = 0; j < N; ++j) {
      if (crash.is_down(j)) continue;  // SPEC §6c: frozen while down
      if (new_commit[j]) timer[j] = 0;
      else if (!reset[j]) timer[j] += 1;
    }
  }

  // One SPEC §9 switch round (either fault model): P0/P1/P2/P3/P7 are
  // round_direct's flat phases verbatim; the P4/P5 tallies and the P6
  // decide gossip route through the K aggregators. Each aggregator
  // combines its segment's live votes into (count, vmax, vmin) and
  // SERVES (count, value) only when the segment is value-UNIFORM (a
  // mixed segment is the switch-vs-replica inconsistency a receiver
  // detects but cannot resolve — it serves nothing). Equivocating
  // support is the per-ROUND stance in BOTH fault models (the switch
  // dedups per-receiver claims) and rides any serving segment (its own
  // segment included). Self votes never travel: a receiver counts
  // itself locally and discounts its own switch-returned copy. Scalar
  // twin of the engines' ops/aggregate.value_votes / min_id_votes.
  void round_switch(uint32_t r) {
    const uint32_t Q = 2 * f + 1;
    const uint32_t K = agg.K;
    std::vector<uint8_t> reset(N, 0), new_commit(N, 0);
    std::vector<uint32_t> views_in;

    // P0 churn.
    if (churn_fires(seed, r, churn_cut))
      for (uint32_t i = 0; i < N; ++i) {
        if (crash.is_down(i)) continue;
        view[i] += 1; timer[i] = 0; reset[i] = 1;
      }

    // P1 view catch-up (flat — view sync is control-plane traffic).
    const std::vector<uint32_t> s_view = view;
    for (uint32_t j = 0; j < N; ++j) {
      if (crash.is_down(j)) continue;  // SPEC §6c: frozen while down
      views_in.clear();
      views_in.push_back(s_view[j]);
      for (uint32_t i = 0; i < N; ++i)
        if (i != j && honest(i) && del(r, i, j))
          views_in.push_back(s_view[i]);
      if (views_in.size() >= f + 1) {
        std::nth_element(views_in.begin(), views_in.begin() + f,
                         views_in.end(), std::greater<uint32_t>());
        uint32_t vth = views_in[f];
        if (vth > view[j]) { view[j] = vth; timer[j] = 0; reset[j] = 1; }
      }
    }

    // P2 timeout.
    for (uint32_t j = 0; j < N; ++j)
      if (!crash.is_down(j) && timer[j] >= view_timeout) {
        view[j] += 1; timer[j] = 0; reset[j] = 1;
      }

    // P3 pre-prepare (shared, flat).
    phase_preprepare(r);

    // Per-sender uplinks: the §6b model sends ONE atomic broadcast
    // into the switch (shared by every phase); the edge model draws a
    // per-phase uplink on the sender's aggregator vertex.
    std::vector<uint8_t> up_ph[3];
    for (uint32_t ph = 0; ph < 3; ++ph) up_ph[ph].assign(N, 0);
    for (uint32_t i = 0; i < N; ++i) {
      if (crash.on && !crash.up[i]) continue;  // down senders send nothing
      if (fault_bcast) {
        const uint8_t u = agg.up_bcast(i) ? 1 : 0;
        up_ph[0][i] = up_ph[1][i] = up_ph[2][i] = u;
      } else {
        for (uint32_t ph = 0; ph < 3; ++ph)
          up_ph[ph][i] = agg.up_edge(ph, i) ? 1 : 0;
      }
    }
    // Per-round equivocation stances (value-blind switch support).
    std::vector<uint8_t> eq_send(N, 0);
    if (equiv && n_byz > 0)
      for (uint32_t i = 0; i < N; ++i)
        if (!honest(i) && stance(r, i)) eq_send[i] = 1;
    // Per-(phase, segment) equivocating-support counts.
    std::vector<uint32_t> eqc[3];
    for (uint32_t ph = 0; ph < 3; ++ph) {
      eqc[ph].assign(K, 0);
      for (uint32_t i = 0; i < N; ++i)
        if (eq_send[i] && up_ph[ph][i]) ++eqc[ph][agg.agg_of(i)];
    }
    // §9b uplink lies: one forged (vote, value) claim per (round, byz
    // node), shared by both vote phases and every slot (the engines'
    // ops/aggregate.uplink_lies). The claim joins its segment's
    // combine — count rides the total, forged value folds into the
    // uniformity check — so a single liar among honest contributors
    // suppresses its whole segment, while an all-liar segment serves
    // the forged value outright. up_ph already folds §6c crash, so a
    // crashed liar claims nothing.
    std::vector<uint8_t> lie_act(N, 0);
    std::vector<uint32_t> lie_v(N, 0);
    if (agg.uplink_cut && n_byz > 0)
      for (uint32_t i = N - n_byz; i < N; ++i)
        if (agg.lies(i)) { lie_act[i] = 1; lie_v[i] = agg.lie_val(i); }

    const std::vector<uint8_t> s_seen = pp_seen;
    const std::vector<uint32_t> s_val = pp_val;
    std::vector<uint32_t> cnt(K), vmx(K), mid(K), mval(K);
    std::vector<uint8_t> srv(K);

    // Segment aggregates for one (phase, slot): live contributors are
    // honest, uplink-delivered holders of `relevant`.
    const auto aggregate = [&](uint32_t ph, uint32_t s,
                               const std::vector<uint8_t>& relevant) {
      std::fill(cnt.begin(), cnt.end(), 0);
      std::vector<uint32_t> vmn(K, 0);
      const auto fold = [&](uint32_t a, uint32_t v) {
        if (cnt[a] == 0) { vmx[a] = v; vmn[a] = v; }
        else { vmx[a] = std::max(vmx[a], v); vmn[a] = std::min(vmn[a], v); }
        ++cnt[a];
      };
      for (uint32_t i = 0; i < N; ++i)
        if (honest(i) && relevant[at(i, s)] && up_ph[ph][i])
          fold(agg.agg_of(i), s_val[at(i, s)]);
      for (uint32_t i = 0; i < N; ++i)
        if (lie_act[i] && up_ph[ph][i]) fold(agg.agg_of(i), lie_v[i]);
      for (uint32_t a = 0; a < K; ++a)
        srv[a] = cnt[a] > 0 && vmx[a] == vmn[a];
    };
    // The switch-delivered count at receiver j (self excluded; own
    // returned copy discounted by the caller's self flag).
    const auto count_for = [&](uint32_t ph, uint32_t s, uint32_t j,
                               bool own_contrib) -> uint32_t {
      const uint32_t v = s_val[at(j, s)];
      uint32_t c = 0;
      for (uint32_t a = 0; a < K; ++a) {
        if (!agg.down(ph, a, j)) continue;
        // §9b: a poisoned delivered aggregator overrides its serve —
        // forged full-segment population, matched to the receiver's
        // own value by construction (no uniformity check, no eq
        // rider — the forged combine replaces the real one entirely).
        if (agg.poisoned(ph, a)) { c += agg.width(a); continue; }
        if (!srv[a] || vmx[a] != v) continue;
        c += cnt[a] + eqc[ph][a];
      }
      const uint32_t aj = agg.agg_of(j);
      if (agg.down(ph, aj, j)) {
        if (agg.poisoned(ph, aj)) {
          // The forged width already counts every segment id once —
          // discount the receiver's own slot iff it contributes
          // locally (the caller adds that self vote); an equivocating
          // stance never rode the poisoned serve.
          if (own_contrib) --c;
        } else if (srv[aj] && vmx[aj] == v) {
          if (own_contrib && up_ph[ph][j]) --c;     // own vote returned
          if (eq_send[j] && up_ph[ph][j]) --c;      // own stance returned
        }
      }
      return c;
    };

    for (uint32_t s = 0; s < S; ++s) {
      // P4 prepare tally (value-matched; self counted locally).
      aggregate(0, s, s_seen);
      for (uint32_t j = 0; j < N; ++j) {
        if (crash.is_down(j)) continue;
        if (!s_seen[at(j, s)] || prepared[at(j, s)]) continue;
        const bool own = honest(j) && s_seen[at(j, s)];
        uint32_t c = (own ? 1 : 0) + count_for(0, s, j, own);
        if (c >= Q) prepared[at(j, s)] = 1;
      }
      // P5 commit tally over post-P4 prepared.
      aggregate(1, s, prepared);
      for (uint32_t j = 0; j < N; ++j) {
        if (crash.is_down(j)) continue;
        if (!prepared[at(j, s)] || committed[at(j, s)]) continue;
        const bool own = honest(j);  // prepared[at(j, s)] holds here
        uint32_t c = (own ? 1 : 0) + count_for(1, s, j, own);
        if (c >= Q) {
          committed[at(j, s)] = 1;
          dval[at(j, s)] = pp_val[at(j, s)];
          new_commit[j] = 1;
        }
      }
      // P6 decide gossip: each aggregator serves the MIN id of its
      // live deciders + that decider's value; receivers adopt from the
      // lowest id across delivered segments.
      for (uint32_t a = 0; a < K; ++a) mid[a] = N;
      for (uint32_t i = 0; i < N; ++i) {
        if (!honest(i) || !committed[at(i, s)] || !up_ph[2][i]) continue;
        const uint32_t a = agg.agg_of(i);
        if (i < mid[a]) { mid[a] = i; mval[a] = dval[at(i, s)]; }
      }
      for (uint32_t j = 0; j < N; ++j) {
        if (crash.is_down(j)) continue;
        if (committed[at(j, s)]) continue;
        uint32_t best = N, bv = 0;
        for (uint32_t a = 0; a < K; ++a) {
          if (mid[a] == N || mid[a] >= best) continue;
          if (!agg.down(2, a, j)) continue;
          best = mid[a]; bv = mval[a];
        }
        if (best < N) {
          committed[at(j, s)] = 1;
          dval[at(j, s)] = bv;
          new_commit[j] = 1;
        }
      }
    }

    // P7 timer.
    for (uint32_t j = 0; j < N; ++j) {
      if (crash.is_down(j)) continue;  // SPEC §6c: frozen while down
      if (new_commit[j]) timer[j] = 0;
      else if (!reset[j]) timer[j] += 1;
    }
  }
};

// ---------------------------------------------------------------------------
// Multi-decree Paxos (SPEC §5).
// ---------------------------------------------------------------------------

struct PaxosSim {
  uint64_t seed;
  uint32_t N, R, S, P;
  uint32_t drop_cut, part_cut, churn_cut;
  uint32_t delivery = DELIVERY_AUTO;
  // SPEC §6c / §A.2 adversary knobs (0 = off).
  uint32_t crash_cut = 0, recover_cut = 0, max_crashed = 0, max_delay = 0;
  CrashAdv crash;
  // SPEC §9 switch model: promise (phase 0) and accepted (phase 1)
  // responses route through K aggregators; the request legs (prepare/
  // accept/decide broadcasts) stay flat.
  uint32_t net_switch = 0, n_agg = 0;
  uint32_t agg_fail_cut = 0, agg_stale_cut = 0, agg_max_stale = 1;
  AggNet agg;

  bool resp_leg(uint32_t ph, uint32_t a, uint32_t p) const {
    if (!net_switch) return net.delivered(a, p);
    if (crash.on && !crash.up[a]) return false;
    return agg.two_hop(ph, a, p);
  }

  // Auto: the round only ever queries proposer↔acceptor edges — ~7·P·N
  // mixer evals edge-wise vs N² materialized — so the crossover sits at
  // P ≈ N/7: a capped proposer set (the SPEC §5 analog of the Raft cap)
  // goes edge-wise, the all-propose default stays dense.
  bool edge_net() const {
    if (delivery == DELIVERY_AUTO) return 7ull * P < N;
    return delivery == DELIVERY_EDGE;
  }

  std::vector<uint32_t> promised, acc_bal, acc_val, learned_val;  // [N*S]
  std::vector<uint8_t> learned_mask;                              // [N*S]
  Net net;

  size_t at(uint32_t n, uint32_t s) const { return size_t(n) * S + s; }

  void run() {
    promised.assign(size_t(N) * S, 0);
    acc_bal.assign(size_t(N) * S, 0);
    acc_val.assign(size_t(N) * S, 0);
    learned_val.assign(size_t(N) * S, 0);
    learned_mask.assign(size_t(N) * S, 0);

    const uint32_t majority = N / 2 + 1;
    std::vector<uint32_t> slot(P), bal(P), vown(P), n_prom(P), n_acc(P);
    std::vector<uint32_t> best_bal(P), best_val(P), v_chosen(P);
    std::vector<uint8_t> proceed(P), decided(P);
    // Scratch per acceptor: per-slot max with a touched list (O(P) reset).
    std::vector<uint32_t> scratch(S, 0);
    std::vector<uint32_t> touched;
    touched.reserve(P);

    crash.init(N, crash_cut);
    for (uint32_t r = 0; r < R; ++r) {
      // SPEC §6c prologue: promised[] is the volatile state (safe —
      // ballots strictly increase across rounds); acceptor history and
      // learner state persist. A down node's flights die via Net's up
      // mask; a down proposer therefore never gathers promises, and a
      // down acceptor's per-slot writes never trigger (its touched
      // lists stay empty) — only the learner loop needs a guard.
      crash.advance(seed, r, crash_cut, recover_cut, max_crashed);
      if (crash.on)
        for (uint32_t i = 0; i < N; ++i)
          if (crash.rec[i])
            for (uint32_t s = 0; s < S; ++s) promised[at(i, s)] = 0;
      net.begin_round(seed, N, r, drop_cut, part_cut, edge_net(), max_delay,
                      crash.up_mask());
      if (net_switch)
        agg.begin_round(seed, N, n_agg, r, drop_cut, part_cut, max_delay,
                        agg_fail_cut, agg_stale_cut, agg_max_stale);
      const bool churn = churn_fires(seed, r, churn_cut);
      for (uint32_t p = 0; p < P; ++p) {
        slot[p] = random_u32(seed, STREAM_VALUE, r, 1, p) % S;
        bal[p] = r * N + p + 1;
        vown[p] = random_u32(seed, STREAM_VALUE, r, 0, p);
        n_prom[p] = n_acc[p] = best_bal[p] = best_val[p] = 0;
        proceed[p] = decided[p] = 0;
      }
      const bool props_active = !churn;

      // Pass 1 per acceptor: prepares → promises; apply new_promised.
      if (props_active) {
        for (uint32_t a = 0; a < N; ++a) {
          touched.clear();
          for (uint32_t p = 0; p < P; ++p)
            if (net.delivered(p, a)) {
              uint32_t s = slot[p];
              if (scratch[s] == 0) touched.push_back(s);
              scratch[s] = std::max(scratch[s], bal[p]);
            }
          for (uint32_t p = 0; p < P; ++p) {
            if (!net.delivered(p, a) || !resp_leg(0, a, p)) continue;
            uint32_t s = slot[p];
            // promise iff b > promised_old and b == max(promised_old, P_max)
            if (bal[p] > promised[at(a, s)] && bal[p] == scratch[s]) {
              ++n_prom[p];
              uint32_t rb = acc_bal[at(a, s)];
              if (rb > best_bal[p]) {  // strict > keeps lowest acceptor id
                best_bal[p] = rb;
                best_val[p] = acc_val[at(a, s)];
              }
            }
          }
          for (uint32_t s : touched) {
            promised[at(a, s)] = std::max(promised[at(a, s)], scratch[s]);
            scratch[s] = 0;
          }
        }
      }

      // Proposer gate + value choice.
      for (uint32_t p = 0; p < P && props_active; ++p) {
        proceed[p] = n_prom[p] >= majority;
        v_chosen[p] = best_bal[p] > 0 ? best_val[p] : vown[p];
      }

      // Pass 2 per acceptor: accepts (reads before writes), responses.
      if (props_active) {
        for (uint32_t a = 0; a < N; ++a) {
          touched.clear();
          for (uint32_t p = 0; p < P; ++p) {
            if (!proceed[p] || !net.delivered(p, a)) continue;
            uint32_t s = slot[p];
            if (bal[p] >= promised[at(a, s)]) {  // promised == new_promised here
              if (scratch[s] == 0) touched.push_back(s);
              scratch[s] = std::max(scratch[s], bal[p]);
            }
          }
          for (uint32_t p = 0; p < P; ++p) {  // responses before application
            if (!proceed[p] || !net.delivered(p, a) || !resp_leg(1, a, p))
              continue;
            uint32_t s = slot[p];
            if (bal[p] >= promised[at(a, s)] && bal[p] == scratch[s]) ++n_acc[p];
          }
          for (uint32_t s : touched) {
            uint32_t am = scratch[s];
            uint32_t pstar = am - (r * N + 1);
            acc_bal[at(a, s)] = am;
            acc_val[at(a, s)] = v_chosen[pstar];
            promised[at(a, s)] = am;
            scratch[s] = 0;
          }
        }
        for (uint32_t p = 0; p < P; ++p)
          decided[p] = proceed[p] && n_acc[p] >= majority;
      }

      // Learn: lowest-id decider per slot, first-learned-wins.
      for (uint32_t n = 0; n < N; ++n)
        for (uint32_t p = 0; p < P; ++p) {
          if (crash.is_down(n)) break;  // SPEC §6c: frozen while down
          if (!decided[p]) continue;
          if (p != n && !net.delivered(p, n)) continue;
          uint32_t s = slot[p];
          if (!learned_mask[at(n, s)]) {
            learned_mask[at(n, s)] = 1;
            learned_val[at(n, s)] = v_chosen[p];
          }
        }
    }
  }
};

// ---------------------------------------------------------------------------
// DPoS (SPEC §7). O(V) per round: one producer row, no N×N matrix.
// ---------------------------------------------------------------------------

struct DposSim {
  uint64_t seed;
  uint32_t V, R, L, C, K, epoch_len;
  uint32_t drop_cut, part_cut, churn_cut;
  // SPEC §6c / §A.1 / §A.2 adversary knobs (0 = off).
  uint32_t crash_cut = 0, recover_cut = 0, max_crashed = 0;
  uint32_t miss_cut = 0, max_delay = 0;
  // SPEC §A.4 correlated producer suppression: one draw per
  // (round / suppress_window, producer) — a suppressed producer misses
  // EVERY slot scheduled inside the window.
  uint32_t suppress_cut = 0, suppress_window = 16;
  CrashAdv crash;

  std::vector<uint32_t> chain_r, chain_p;  // [V*L]
  std::vector<uint32_t> chain_len;         // [V]
  std::vector<int32_t> lib;                // [V] SPEC §7 LIB index, -1 none

  // SPEC §7 LIB: largest local index k with >= T = 2K/3+1 distinct
  // producers among the blocks after k. Computed once from the final
  // chains; twin of engines/dpos.py lib_index.
  void compute_lib() {
    lib.assign(V, -1);
    const uint32_t T = (2 * K) / 3 + 1;
    if (T > C) return;
    std::vector<int32_t> last_occ(C);
    for (uint32_t v = 0; v < V; ++v) {
      std::fill(last_occ.begin(), last_occ.end(), -1);
      for (uint32_t k = 0; k < chain_len[v]; ++k)
        last_occ[chain_p[size_t(v) * L + k]] = int32_t(k);
      std::nth_element(last_occ.begin(), last_occ.begin() + (T - 1),
                       last_occ.end(), std::greater<int32_t>());
      lib[v] = std::max(last_occ[T - 1] - 1, -1);
    }
  }

  void run() {
    chain_r.assign(size_t(V) * L, 0);
    chain_p.assign(size_t(V) * L, 0);
    chain_len.assign(V, 0);

    std::vector<uint32_t> stake(V);
    for (uint32_t v = 0; v < V; ++v)
      stake[v] = random_u32(seed, STREAM_STAKE, 0, 0, v) % 1000 + 1;

    const uint32_t E = (R + epoch_len - 1) / epoch_len;
    std::vector<uint32_t> producers(size_t(E) * K);
    std::vector<uint64_t> tally(C);
    std::vector<uint32_t> order(C);
    for (uint32_t e = 0; e < E; ++e) {
      std::fill(tally.begin(), tally.end(), 0);
      for (uint32_t v = 0; v < V; ++v)
        tally[random_u32(seed, STREAM_VOTE, e, 0, v) % C] += stake[v];
      for (uint32_t c = 0; c < C; ++c) order[c] = c;
      std::stable_sort(order.begin(), order.end(),
                       [&](uint32_t a, uint32_t b) { return tally[a] > tally[b]; });
      for (uint32_t k = 0; k < K; ++k) producers[size_t(e) * K + k] = order[k];
    }

    crash.init(V, crash_cut);
    for (uint32_t r = 0; r < R; ++r) {
      // SPEC §6c advances EVERY round (churned or not — the down mask
      // is history); the chain is durable, so recovery needs no reset.
      crash.advance(seed, r, crash_cut, recover_cut, max_crashed);
      if (churn_fires(seed, r, churn_cut)) continue;  // producer offline
      uint32_t e = r / epoch_len, t = r % epoch_len;
      uint32_t p = producers[size_t(e) * K + t % K];
      // SPEC §A.1 per-producer slot miss: skipped chain-wide, keyed
      // (round, producer) so failures correlate with the schedule.
      if (miss_cut && random_u32(seed, STREAM_SLOTMISS, r, 0, p) < miss_cut)
        continue;
      // SPEC §A.4 correlated suppression: window-keyed, so the outage
      // persists across the producer's consecutive scheduled slots.
      if (suppress_cut &&
          random_u32(seed, STREAM_SUPPRESS, r / suppress_window, 0, p) <
              suppress_cut)
        continue;
      if (crash.is_down(p)) continue;  // SPEC §6c: down producer, no block
      bool part_active = random_u32(seed, STREAM_PARTITION, r, 0, 0) < part_cut;
      uint32_t side_p = random_u32(seed, STREAM_PARTITION, r, 1, p) & 1u;
      for (uint32_t v = 0; v < V; ++v) {
        if (crash.is_down(v)) continue;  // down validators stop growing
        bool recv;
        if (v == p) {
          recv = true;
        } else {
          recv = delivery_u32(seed, r, p, v) >= drop_cut;
          // SPEC §A.2 delayed retransmission repairs the drop leg only
          // (partitions are topology faults).
          if (!recv && max_delay)
            recv = delayed_open(seed, r, p, v, drop_cut, max_delay);
          if (recv && part_active)
            recv = (random_u32(seed, STREAM_PARTITION, r, 1, v) & 1u) == side_p;
        }
        if (recv && chain_len[v] < L) {
          chain_r[size_t(v) * L + chain_len[v]] = r;
          chain_p[size_t(v) * L + chain_len[v]] = p;
          chain_len[v] += 1;
        }
      }
    }
    compute_lib();
  }
};

// ---------------------------------------------------------------------------
// Chained HotStuff (SPEC §7b). O(N) per round: one leader→node proposal
// row, one node→leader vote row, one threshold count — the scalar twin
// of engines/hotstuff.py (the PR 5 aggregate-round pattern: the oracle
// implements the same linear-communication phases straight from the
// SPEC definition, never via the engine's array formulation).
// ---------------------------------------------------------------------------

struct HotstuffSim {
  uint64_t seed;
  uint32_t N, R, S, f, view_timeout, n_byz;
  uint32_t equiv = 0;  // byz_mode == "equivocate" (SPEC §7c fork model)
  uint32_t drop_cut, part_cut, churn_cut;
  // SPEC §6c / §A.2 adversary knobs (0 = off).
  uint32_t crash_cut = 0, recover_cut = 0, max_crashed = 0, max_delay = 0;
  CrashAdv crash;
  // SPEC §9 switch model (votes via K aggregators; phase 0).
  uint32_t net_switch = 0, n_agg = 0;
  uint32_t agg_fail_cut = 0, agg_stale_cut = 0, agg_max_stale = 1;
  AggNet agg;
  // SPEC §B view-synchronizer timer skew (0 = off).
  uint32_t desync_cut = 0, max_skew = 1;

  // SPEC §7c fork-certificate table depth — mirrors
  // engines/hotstuff.py FORK_TABLE (at most this many forked QCs are
  // value-tracked; later forks still alter nothing durable).
  static constexpr uint32_t FORK_TABLE = 8;

  // QC-chain state (the network's shared chain — without an
  // equivocating leader forks are unreachable: a QC certifies one
  // block per height and the next proposal extends the newest QC;
  // SPEC §7c re-admits them via per-receiver proposal variants and
  // double-voting byzantine replicas). The PACEMAKER is per node since
  // the SPEC §B view-synchronizer PR: view_[i]/timer[i] below advance
  // on locally observed progress and local timeouts only.
  uint32_t gcommit = 0;
  int32_t b1_v = -1, b1_h = -1, b2_v = -1, b2_h = -1, b3_v = -1, b3_h = -1;
  std::vector<int32_t> chain_view;  // [S]; -1 = height never certified
  std::vector<int32_t> chain_vid;   // [S] §7c canonical value-id (0/1)
  // §7c fork certificates: entry k = a forked QC's (view, height);
  // fvec bit k marks the honest receivers shown the NON-canonical
  // variant at that fork — their decided value diverges there.
  std::vector<uint32_t> fvec;       // [N]
  int32_t ftab_v[FORK_TABLE], ftab_h[FORK_TABLE];
  uint32_t fnum = 0;
  // Per-node state: pacemaker sync (volatile) + committed prefix
  // (persistent, SPEC §6c).
  std::vector<uint32_t> view_, timer, clen;     // [N]
  std::vector<uint8_t> committed;               // [N*S], filled at end
  std::vector<uint32_t> dval;                   // [N*S], filled at end

  bool honest(uint32_t i) const { return i < N - n_byz; }

  void run() {
    gcommit = 0;
    b1_v = b1_h = b2_v = b2_h = b3_v = b3_h = -1;
    chain_view.assign(S, -1);
    chain_vid.assign(S, 0);
    fvec.assign(N, 0);
    for (uint32_t k = 0; k < FORK_TABLE; ++k) ftab_v[k] = ftab_h[k] = -1;
    fnum = 0;
    view_.assign(N, 0);
    timer.assign(N, 0);
    clen.assign(N, 0);
    crash.init(N, crash_cut);
    for (uint32_t r = 0; r < R; ++r) round(r);
    committed.assign(size_t(N) * S, 0);
    dval.assign(size_t(N) * S, 0);
    for (uint32_t n = 0; n < N; ++n)
      for (uint32_t s = 0; s < clen[n]; ++s) {
        committed[size_t(n) * S + s] = 1;
        // SPEC §7b block value: a pure counter function of
        // (certifying view, height) — recomputed here exactly as the
        // engine's extraction epilogue recomputes it. §7c: subdraw 6
        // is the equivocating sibling variant (a forked QC's canonical
        // side is always variant 0, so chain_vid == 1 only at
        // non-forked byz-certified heights).
        dval[size_t(n) * S + s] = random_u32(
            seed, STREAM_VALUE, uint32_t(chain_view[s]),
            chain_vid[s] == 1 ? 6 : 5, s);
      }
    // §7c deceived overlays: a node holding fork entry k's fvec bit
    // committed the SIBLING variant at that height (ascending k —
    // later entries win, like the engine's select chain).
    for (uint32_t k = 0; k < fnum; ++k) {
      if (ftab_h[k] < 0) continue;
      const uint32_t hh = uint32_t(ftab_h[k]);
      for (uint32_t n = 0; n < N; ++n)
        if (((fvec[n] >> k) & 1u) && hh < clen[n])
          dval[size_t(n) * S + hh] = random_u32(
              seed, STREAM_VALUE, uint32_t(ftab_v[k]), 6, hh);
    }
  }

  void round(uint32_t r) {
    const uint32_t Q = 2 * f + 1;
    // SPEC §6c prologue: advance the down mask; volatile reset on
    // recovery (view/timer rejoin at 0; the committed prefix is the
    // persisted state HotStuff's safety argument rests on).
    crash.advance(seed, r, crash_cut, recover_cut, max_crashed);
    if (crash.on)
      for (uint32_t i = 0; i < N; ++i)
        if (crash.rec[i]) { view_[i] = 0; timer[i] = 0; }
    if (net_switch)
      agg.begin_round(seed, N, n_agg, r, drop_cut, part_cut, max_delay,
                      agg_fail_cut, agg_stale_cut, agg_max_stale);

    // SPEC §B timer-skew injection: the skewed timer crosses
    // view_timeout HERE, before any proposal can reset it — the node
    // abandons its view prematurely (engines/hotstuff.py pre-round
    // timeout). Down nodes draw nothing (the JAX freeze discards
    // their skew). Timers never exceed view_timeout - 1 at round
    // start without skew, so the whole block is gated.
    if (desync_cut)
      for (uint32_t i = 0; i < N; ++i) {
        if (crash.is_down(i)) continue;
        if (random_u32(seed, STREAM_DESYNC, r, 0, i) < desync_cut)
          timer[i] +=
              1 + random_u32(seed, STREAM_DESYNC, r, 1, i) % max_skew;
        if (timer[i] >= view_timeout) { view_[i] += 1; timer[i] = 0; }
      }

    // P0 churn: every would-be proposer skips its slot this round.
    const bool churn = churn_fires(seed, r, churn_cut);
    const bool eqv = equiv && n_byz > 0;
    const bool part_active =
        random_u32(seed, STREAM_PARTITION, r, 0, 0) < part_cut;

    // SPEC §2 openness of the src→j broadcast leg on the absolute
    // edge key (+ §A.2 retransmission; partitions are topology faults
    // — never repaired). Per (round, edge): flights sharing an edge
    // in one round share its fate, exactly like the engine.
    auto bopen = [&](uint32_t src, uint32_t j) {
      bool open = delivery_u32(seed, r, src, j) >= drop_cut;
      if (!open && max_delay)
        open = delayed_open(seed, r, src, j, drop_cut, max_delay);
      return open &&
             (!part_active ||
              (random_u32(seed, STREAM_PARTITION, r, 1, j) & 1u) ==
                  (random_u32(seed, STREAM_PARTITION, r, 1, src) & 1u));
    };

    // P1 highest-view gossip (SPEC §B view-sync message): the
    // highest-view honest live node — lowest id on ties — broadcasts
    // its view; receivers behind it catch up. One O(N) row through
    // the §2 delivery layer.
    int64_t vM = -1;
    uint32_t M = N;
    for (uint32_t i = 0; i < N; ++i)
      if (honest(i) && !crash.is_down(i) && int64_t(view_[i]) > vM) {
        vM = view_[i];
        M = i;
      }
    std::vector<uint8_t> advg(N, 0);
    if (vM >= 0)
      for (uint32_t j = 0; j < N; ++j) {
        if (j == M || crash.is_down(j)) continue;
        if (int64_t(view_[j]) < vM && bopen(M, j)) {
          advg[j] = 1;
          view_[j] = uint32_t(vM);
        }
      }

    // P2 proposal: node i proposes iff ITS view elects it (view[i]
    // mod N == i — the §B per-receiver leader identity) and extends
    // the newest QC at height b1_h + 1. With desynced views several
    // nodes may propose at once; the round's EFFECTIVE proposal is
    // the highest-view one (Vstar — stale proposals lose, and a
    // receiver ignores views below its own). Silent-byzantine and
    // down proposers withhold; under SPEC §7c (equiv) a byzantine
    // proposer DOES propose — two block variants for the same (view,
    // height), each receiver shown one.
    const int32_t h_next = b1_h + 1;
    int64_t Vstar = -1;
    if (!churn && h_next < int32_t(S))
      for (uint32_t i = 0; i < N; ++i) {
        if (view_[i] % N != i || crash.is_down(i)) continue;
        if (!eqv && !honest(i)) continue;
        if (int64_t(view_[i]) > Vstar) Vstar = view_[i];
      }
    const bool exists = Vstar >= 0;
    const uint32_t L = exists ? uint32_t(Vstar) % N : 0;
    const bool byzL = !honest(L);
    const uint32_t start_commit = gcommit;  // what the proposal carries

    std::vector<uint8_t> pdel(N, 0), evid(N, 0);
    if (exists)
      for (uint32_t j = 0; j < N; ++j) {
        if (crash.is_down(j)) continue;  // down receivers hear nothing
        if (int64_t(view_[j]) > Vstar) continue;  // ahead: stale to j
        if (j != L && !bopen(L, j)) continue;
        pdel[j] = 1;
        // §7c per-receiver value-id: which variant the byzantine
        // leader showed j — the pbft family's sup(r, i, j) keying.
        // Honest leaders pin every receiver to variant 0.
        if (eqv && byzL)
          evid[j] = random_u32(seed, STREAM_EQUIV, r, L, j) & 1u;
      }

    // P2 votes: per-variant tallies (SPEC §7c — silent mode keeps one;
    // cnt1 stays 0 there). Byzantine replicas under equiv double-vote
    // for BOTH variants; under §9b a byzantine replica may also LIE to
    // its switch vertex (a claim, not a pinned value — it joins both
    // variant queries), and a poisoned aggregator serves its forged
    // full-segment width to both, which is how a poisoned switch
    // vertex forges a forked QC without real double votes.
    uint32_t cnt0 = 0, cnt1 = 0;
    if (exists && !net_switch) {
      for (uint32_t j = 0; j < N; ++j) {
        if (!pdel[j]) continue;
        // The vote is the return flight on edge (j, L); given pdel, a
        // partition cannot separate the pair again within the round.
        bool vd = j == L;
        if (!vd) {
          bool open = delivery_u32(seed, r, j, L) >= drop_cut;
          if (!open && max_delay)
            open = delayed_open(seed, r, j, L, drop_cut, max_delay);
          vd = open;
        }
        if (!vd) continue;
        if (honest(j)) {
          (eqv && evid[j] ? cnt1 : cnt0) += 1;
        } else if (eqv) {
          ++cnt0; ++cnt1;  // §7c maximal double-vote
        }
      }
    } else if (exists) {
      // SPEC §9: votes route through the K aggregators (phase 0); the
      // leader sees K pre-aggregated segment counts. Scalar twin of
      // the engine's _count over ops/aggregate primitives.
      const uint32_t K = agg.K;
      std::vector<uint32_t> seg0(K, 0), seg1(K, 0);
      bool s0 = false, s1 = false;  // the leader's local self claim
      for (uint32_t i = 0; i < N; ++i) {
        const bool crashed = crash.on && !crash.up[i];
        const bool voted = pdel[i] && honest(i);
        // §9b uplink lie: a byz node claims a vote regardless of
        // delivery — and, under equiv, for both variants.
        const bool claim = (!honest(i)) &&
                           ((eqv && pdel[i]) || agg.lies(i));
        const bool sup0 = eqv ? ((voted && evid[i] == 0) || claim)
                              : (voted || claim);
        const bool sup1 = eqv && ((voted && evid[i] == 1) || claim);
        if (i == L) {
          // The leader counts itself locally (no uplink gate); silent
          // mode adds only its real vote, never a lie.
          s0 = eqv ? sup0 : voted;
          s1 = sup1;
          continue;  // self never travels
        }
        if (crashed || !agg.up_edge(0, i)) continue;  // §6c: crashed
        if (sup0) ++seg0[agg.agg_of(i)];              // liars claim nothing
        if (sup1) ++seg1[agg.agg_of(i)];
      }
      const uint32_t aL = agg.agg_of(L);
      // Leader's own aggregator poisoned+delivered: the forged width
      // already counts L's slot — don't add the local claim.
      const bool ownpz = agg.down(0, aL, L) && agg.poisoned(0, aL);
      cnt0 = (s0 && !ownpz) ? 1 : 0;
      cnt1 = (s1 && !ownpz) ? 1 : 0;
      for (uint32_t a = 0; a < K; ++a) {
        if (!agg.down(0, a, L)) continue;
        if (agg.poisoned(0, a)) {
          const uint32_t w = agg.width(a);
          cnt0 += w;
          if (eqv) cnt1 += w;
          continue;
        }
        cnt0 += seg0[a];
        cnt1 += seg1[a];
      }
    }

    // P3 QC-chain shift + chained 3-chain commit (consecutive views).
    // §7c per-value QC tally: each variant needs its own quorum; BOTH
    // reaching Q in one view is a FORKED QC — the safety violation the
    // byzantine model deliberately re-admits. The canonical chain
    // prefers variant 0 (deterministic tie-break, mirrored in the
    // engine).
    const bool qc0 = exists && cnt0 >= Q;
    const bool qc1 = eqv && exists && cnt1 >= Q;
    const bool qc = qc0 || qc1;
    const bool forked = qc0 && qc1;
    if (qc) {
      b3_v = b2_v; b3_h = b2_h;
      b2_v = b1_v; b2_h = b1_h;
      b1_v = int32_t(Vstar); b1_h = h_next;
      chain_view[h_next] = int32_t(Vstar);
      if (eqv) chain_vid[h_next] = qc0 ? 0 : 1;
      if (b3_v >= 0 && b1_v == b2_v + 1 && b2_v == b3_v + 1)
        gcommit = std::max(gcommit, uint32_t(b3_h + 1));
    }
    // §7c fork-certificate table: record (view, height) and mark every
    // honest receiver shown the NON-canonical variant — those nodes
    // durably believe the sibling block sits at this height.
    if (forked && fnum < FORK_TABLE) {
      ftab_v[fnum] = int32_t(Vstar);
      ftab_h[fnum] = h_next;
      for (uint32_t j = 0; j < N; ++j)
        if (pdel[j] && honest(j) && evid[j] == 1)
          fvec[j] |= (1u << fnum);
      ++fnum;
    }

    // P6 learning + QC-notify: the proposal carries the proposer's
    // view and the commit state as of proposal time; when the QC
    // forms, the same open channels carry the certificate back out,
    // so receivers enter view Vstar + 1 — the within-round notify the
    // chained pipeline's consecutive-view rule needs.
    for (uint32_t j = 0; j < N; ++j)
      if (pdel[j]) {
        view_[j] = uint32_t(Vstar) + (qc ? 1u : 0u);
        clen[j] = std::max(clen[j], start_commit);
      }

    // P7 per-node pacemaker: progress (a delivered proposal or a
    // view-sync catch-up) resets the local timer; otherwise the
    // node's OWN view changes after view_timeout local rounds.
    for (uint32_t j = 0; j < N; ++j) {
      if (crash.is_down(j)) continue;
      const bool progress = pdel[j] || advg[j];
      const bool to = !progress && timer[j] + 1 >= view_timeout;
      if (to) view_[j] += 1;
      timer[j] = (progress || to) ? 0 : timer[j] + 1;
    }
  }
};

}  // namespace

// ---------------------------------------------------------------------------
// Engine trait impls (engine.h) — the native `Consensus`-trait seam.
// Each adapter owns a Sim, maps SimConfig onto it, and exposes the
// decided log as canonical (a, b) records; the CLI never sees a Sim.
// ---------------------------------------------------------------------------

namespace {

// SPEC §9 config validation shared by the switch-capable adapters AND
// the C ABI entry points (mirrors core/config.py: flat forbids the agg
// knobs, switch needs 1 <= K <= N, the stale depth is bounded like the
// §A.2 horizon). ONE rule, five call sites — a future bound change
// edits exactly here.
bool valid_switch(uint32_t net_switch, uint32_t n_aggregators,
                  uint32_t n_nodes, uint32_t agg_fail_cut,
                  uint32_t agg_stale_cut, uint32_t agg_max_stale) {
  if (agg_max_stale < 1 || agg_max_stale > 8) return false;
  if (!net_switch)
    return n_aggregators == 0 && agg_fail_cut == 0 &&
           agg_stale_cut == 0 && agg_max_stale == 1;
  return n_aggregators >= 1 && n_aggregators <= n_nodes;
}

// SPEC §9b poison-knob validation (mirrors core/config.py): flat
// forbids every §9b knob; under switch the byzantine aggregators are a
// tail of [0, K] and a poison rate needs at least one of them.
bool valid_poison(uint32_t net_switch, uint32_t n_aggregators,
                  uint32_t agg_byz, uint32_t agg_poison_cut,
                  uint32_t byz_uplink_cut) {
  if (!net_switch)
    return agg_byz == 0 && agg_poison_cut == 0 && byz_uplink_cut == 0;
  if (agg_byz > n_aggregators) return false;
  return agg_poison_cut == 0 || agg_byz > 0;
}

bool valid_switch(const SimConfig& c) {
  return valid_switch(c.net_switch, c.n_aggregators, c.n_nodes,
                      c.agg_fail_cut, c.agg_stale_cut, c.agg_max_stale) &&
         valid_poison(c.net_switch, c.n_aggregators, c.agg_byz,
                      c.agg_poison_cut, c.byz_uplink_cut);
}

class RaftEngine final : public Engine {
 public:
  const char* name() const override { return "raft"; }
  int run(const SimConfig& c) override {
    if (c.n_nodes == 0 || c.t_max <= c.t_min || c.max_active > c.n_nodes ||
        c.oracle_delivery > DELIVERY_EDGE || !valid_switch(c) ||
        c.agg_byz || c.agg_poison_cut || c.byz_uplink_cut)  // §9b: BFT only
      return 1;
    sim_.seed = c.seed; sim_.N = c.n_nodes; sim_.R = c.n_rounds;
    sim_.L = c.log_capacity; sim_.E = c.max_entries;
    sim_.t_min = c.t_min; sim_.t_max = c.t_max;
    sim_.drop_cut = c.drop_cut; sim_.part_cut = c.part_cut;
    sim_.churn_cut = c.churn_cut;
    sim_.A = c.max_active;
    sim_.n_byz = c.n_byzantine; sim_.byz_equiv = c.byz_equivocate;
    sim_.delivery = c.oracle_delivery;
    sim_.crash_cut = c.crash_cut; sim_.recover_cut = c.recover_cut;
    sim_.max_crashed = c.max_crashed; sim_.max_delay = c.max_delay;
    sim_.net_switch = c.net_switch; sim_.n_agg = c.n_aggregators;
    sim_.agg_fail_cut = c.agg_fail_cut; sim_.agg_stale_cut = c.agg_stale_cut;
    sim_.agg_max_stale = c.agg_max_stale;
    sim_.run();
    return 0;
  }
  uint32_t n_nodes() const override { return sim_.N; }
  uint32_t decided_count(uint32_t n) const override { return sim_.commit[n]; }
  void decided_records(uint32_t n, uint32_t* a, uint32_t* b) const override {
    for (uint32_t k = 0; k < sim_.commit[n]; ++k) {
      a[k] = sim_.log_term[size_t(n) * sim_.L + k];
      b[k] = sim_.log_val[size_t(n) * sim_.L + k];
    }
  }

 private:
  RaftSim sim_;
};

// Shared shape for the two [node, slot] sparse-decided protocols.
template <typename Sim>
class SlotEngine : public Engine {
 public:
  uint32_t n_nodes() const override { return sim_.N; }
  uint32_t decided_count(uint32_t n) const override {
    uint32_t c = 0;
    for (uint32_t s = 0; s < slots(); ++s) c += mask()[size_t(n) * slots() + s] ? 1 : 0;
    return c;
  }
  void decided_records(uint32_t n, uint32_t* a, uint32_t* b) const override {
    uint32_t k = 0;
    for (uint32_t s = 0; s < slots(); ++s)
      if (mask()[size_t(n) * slots() + s]) {
        a[k] = s;
        b[k] = vals()[size_t(n) * slots() + s];
        ++k;
      }
  }

 protected:
  virtual uint32_t slots() const = 0;
  virtual const uint8_t* mask() const = 0;
  virtual const uint32_t* vals() const = 0;
  Sim sim_;
};

class PbftEngine final : public SlotEngine<PbftSim> {
 public:
  const char* name() const override { return "pbft"; }
  int run(const SimConfig& c) override {
    if (c.n_nodes != 3 * c.f + 1 || c.n_byzantine > c.f ||
        c.oracle_delivery > DELIVERY_EDGE || !valid_switch(c) ||
        c.max_skew < 1 || c.max_skew > 8)  // SPEC §B skew bound
      return 1;
    sim_.seed = c.seed; sim_.N = c.n_nodes; sim_.R = c.n_rounds;
    sim_.S = c.log_capacity; sim_.f = c.f;
    sim_.view_timeout = c.view_timeout; sim_.n_byz = c.n_byzantine;
    sim_.equiv = c.byz_equivocate;
    sim_.fault_bcast = c.fault_bcast;
    sim_.desync_cut = c.desync_cut; sim_.max_skew = c.max_skew;
    sim_.drop_cut = c.drop_cut; sim_.part_cut = c.part_cut;
    sim_.churn_cut = c.churn_cut;
    sim_.delivery = c.oracle_delivery;
    sim_.crash_cut = c.crash_cut; sim_.recover_cut = c.recover_cut;
    sim_.max_crashed = c.max_crashed; sim_.max_delay = c.max_delay;
    sim_.net_switch = c.net_switch; sim_.n_agg = c.n_aggregators;
    sim_.agg_fail_cut = c.agg_fail_cut; sim_.agg_stale_cut = c.agg_stale_cut;
    sim_.agg_max_stale = c.agg_max_stale;
    sim_.agg.agg_byz = c.agg_byz;           // SPEC §9b
    sim_.agg.poison_cut = c.agg_poison_cut;
    sim_.agg.uplink_cut = c.byz_uplink_cut;
    sim_.run();
    return 0;
  }

 protected:
  uint32_t slots() const override { return sim_.S; }
  const uint8_t* mask() const override { return sim_.committed.data(); }
  const uint32_t* vals() const override { return sim_.dval.data(); }
};

class PaxosEngine final : public SlotEngine<PaxosSim> {
 public:
  const char* name() const override { return "paxos"; }
  int run(const SimConfig& c) override {
    if (c.n_nodes == 0 || c.log_capacity == 0 ||
        c.oracle_delivery > DELIVERY_EDGE || !valid_switch(c) ||
        c.agg_byz || c.agg_poison_cut || c.byz_uplink_cut)  // §9b: BFT only
      return 1;
    sim_.seed = c.seed; sim_.N = c.n_nodes; sim_.R = c.n_rounds;
    sim_.S = c.log_capacity;
    sim_.P = c.n_proposers ? c.n_proposers : c.n_nodes;
    sim_.drop_cut = c.drop_cut; sim_.part_cut = c.part_cut;
    sim_.churn_cut = c.churn_cut;
    sim_.delivery = c.oracle_delivery;
    sim_.crash_cut = c.crash_cut; sim_.recover_cut = c.recover_cut;
    sim_.max_crashed = c.max_crashed; sim_.max_delay = c.max_delay;
    sim_.net_switch = c.net_switch; sim_.n_agg = c.n_aggregators;
    sim_.agg_fail_cut = c.agg_fail_cut; sim_.agg_stale_cut = c.agg_stale_cut;
    sim_.agg_max_stale = c.agg_max_stale;
    sim_.run();
    return 0;
  }

 protected:
  uint32_t slots() const override { return sim_.S; }
  const uint8_t* mask() const override { return sim_.learned_mask.data(); }
  const uint32_t* vals() const override { return sim_.learned_val.data(); }
};

class HotstuffEngine final : public SlotEngine<HotstuffSim> {
 public:
  const char* name() const override { return "hotstuff"; }
  int run(const SimConfig& c) override {
    if (c.n_nodes != 3 * c.f + 1 || c.n_byzantine > c.f ||
        !valid_switch(c) || c.max_skew < 1 || c.max_skew > 8)
      return 1;
    sim_.seed = c.seed; sim_.N = c.n_nodes; sim_.R = c.n_rounds;
    sim_.S = c.log_capacity; sim_.f = c.f;
    sim_.view_timeout = c.view_timeout; sim_.n_byz = c.n_byzantine;
    sim_.equiv = c.byz_equivocate;  // SPEC §7c fork model
    sim_.desync_cut = c.desync_cut; sim_.max_skew = c.max_skew;
    sim_.drop_cut = c.drop_cut; sim_.part_cut = c.part_cut;
    sim_.churn_cut = c.churn_cut;
    sim_.crash_cut = c.crash_cut; sim_.recover_cut = c.recover_cut;
    sim_.max_crashed = c.max_crashed; sim_.max_delay = c.max_delay;
    sim_.net_switch = c.net_switch; sim_.n_agg = c.n_aggregators;
    sim_.agg_fail_cut = c.agg_fail_cut; sim_.agg_stale_cut = c.agg_stale_cut;
    sim_.agg_max_stale = c.agg_max_stale;
    sim_.agg.agg_byz = c.agg_byz;           // SPEC §9b
    sim_.agg.poison_cut = c.agg_poison_cut;
    sim_.agg.uplink_cut = c.byz_uplink_cut;
    sim_.run();
    return 0;
  }

 protected:
  uint32_t slots() const override { return sim_.S; }
  const uint8_t* mask() const override { return sim_.committed.data(); }
  const uint32_t* vals() const override { return sim_.dval.data(); }
};

class DposEngine final : public Engine {
 public:
  const char* name() const override { return "dpos"; }
  int run(const SimConfig& c) override {
    if (c.n_nodes == 0 || c.n_candidates == 0 || c.n_producers == 0 ||
        c.n_producers > c.n_candidates || c.n_candidates > c.n_nodes ||
        c.epoch_len == 0 || c.net_switch || c.suppress_window == 0)
      return 1;
    sim_.seed = c.seed; sim_.V = c.n_nodes; sim_.R = c.n_rounds;
    sim_.L = c.log_capacity; sim_.C = c.n_candidates; sim_.K = c.n_producers;
    sim_.epoch_len = c.epoch_len;
    sim_.drop_cut = c.drop_cut; sim_.part_cut = c.part_cut;
    sim_.churn_cut = c.churn_cut;
    sim_.crash_cut = c.crash_cut; sim_.recover_cut = c.recover_cut;
    sim_.max_crashed = c.max_crashed;
    sim_.miss_cut = c.miss_cut; sim_.max_delay = c.max_delay;
    sim_.suppress_cut = c.suppress_cut;
    sim_.suppress_window = c.suppress_window;
    sim_.run();
    return 0;
  }
  uint32_t n_nodes() const override { return sim_.V; }
  uint32_t decided_count(uint32_t v) const override { return sim_.chain_len[v]; }
  void decided_records(uint32_t v, uint32_t* a, uint32_t* b) const override {
    for (uint32_t k = 0; k < sim_.chain_len[v]; ++k) {
      a[k] = sim_.chain_r[size_t(v) * sim_.L + k];
      b[k] = sim_.chain_p[size_t(v) * sim_.L + k];
    }
  }

 private:
  DposSim sim_;
};

}  // namespace

std::unique_ptr<Engine> make_engine(const std::string& protocol) {
  if (protocol == "raft") return std::make_unique<RaftEngine>();
  if (protocol == "pbft") return std::make_unique<PbftEngine>();
  if (protocol == "paxos") return std::make_unique<PaxosEngine>();
  if (protocol == "dpos") return std::make_unique<DposEngine>();
  if (protocol == "hotstuff") return std::make_unique<HotstuffEngine>();
  return nullptr;
}

int protocol_id(const std::string& protocol) {
  if (protocol == "raft") return 0;
  if (protocol == "pbft") return 1;
  if (protocol == "paxos") return 2;
  if (protocol == "dpos") return 3;
  if (protocol == "hotstuff") return 4;
  return -1;
}

}  // namespace ctpu

// ---------------------------------------------------------------------------
// C ABI (ctypes). One call runs one sweep; Python loops sweeps with
// seed_b = base_seed + b (SPEC §1) and serializes via core/serialize.py.
// ---------------------------------------------------------------------------

extern "C" {

int ctpu_raft_run(uint64_t seed, uint32_t n_nodes, uint32_t n_rounds,
                  uint32_t log_capacity, uint32_t max_entries,
                  uint32_t t_min, uint32_t t_max,
                  uint32_t drop_cut, uint32_t part_cut, uint32_t churn_cut,
                  uint32_t max_active,     // 0 = dense; >0 = SPEC §3b cap
                  uint32_t n_byzantine,    // SPEC §3c minority size
                  uint32_t byz_equivocate, // 0 silent, 1 double-grant
                  uint32_t oracle_delivery,  // 0 auto, 1 dense, 2 edge
                  uint32_t crash_cut,      // SPEC §6c crash cutoff
                  uint32_t recover_cut,    // SPEC §6c recovery cutoff
                  uint32_t max_crashed,    // SPEC §6c cap (0 = none)
                  uint32_t max_delay,      // SPEC §A.2 horizon (0 = off)
                  uint32_t net_switch,     // SPEC §9 switch model
                  uint32_t n_aggregators, uint32_t agg_fail_cut,
                  uint32_t agg_stale_cut, uint32_t agg_max_stale,
                  uint32_t* out_commit,    // [N]
                  uint32_t* out_log_term,  // [N*L]
                  uint32_t* out_log_val,   // [N*L]
                  uint32_t* out_term,      // [N]
                  uint32_t* out_role) {    // [N]
  if (n_nodes == 0 || t_max <= t_min || max_active > n_nodes ||
      n_byzantine > n_nodes || oracle_delivery > 2 || max_delay > 16)
    return 1;
  if (!ctpu::valid_switch(net_switch, n_aggregators, n_nodes,
                          agg_fail_cut, agg_stale_cut, agg_max_stale))
    return 1;
  ctpu::RaftSim sim;
  sim.seed = seed; sim.N = n_nodes; sim.R = n_rounds; sim.L = log_capacity;
  sim.E = max_entries; sim.t_min = t_min; sim.t_max = t_max;
  sim.drop_cut = drop_cut; sim.part_cut = part_cut; sim.churn_cut = churn_cut;
  sim.A = max_active;
  sim.n_byz = n_byzantine; sim.byz_equiv = byz_equivocate;
  sim.delivery = oracle_delivery;
  sim.crash_cut = crash_cut; sim.recover_cut = recover_cut;
  sim.max_crashed = max_crashed; sim.max_delay = max_delay;
  sim.net_switch = net_switch; sim.n_agg = n_aggregators;
  sim.agg_fail_cut = agg_fail_cut; sim.agg_stale_cut = agg_stale_cut;
  sim.agg_max_stale = agg_max_stale;
  sim.run();
  std::memcpy(out_commit, sim.commit.data(), sizeof(uint32_t) * n_nodes);
  std::memcpy(out_log_term, sim.log_term.data(),
              sizeof(uint32_t) * size_t(n_nodes) * log_capacity);
  std::memcpy(out_log_val, sim.log_val.data(),
              sizeof(uint32_t) * size_t(n_nodes) * log_capacity);
  std::memcpy(out_term, sim.term.data(), sizeof(uint32_t) * n_nodes);
  std::memcpy(out_role, sim.role.data(), sizeof(uint32_t) * n_nodes);
  return 0;
}

int ctpu_pbft_run(uint64_t seed, uint32_t n_nodes, uint32_t n_rounds,
                  uint32_t n_slots, uint32_t f, uint32_t view_timeout,
                  uint32_t n_byzantine, uint32_t byz_equivocate,
                  uint32_t fault_bcast,     // SPEC §6b broadcast faults
                  uint32_t drop_cut, uint32_t part_cut, uint32_t churn_cut,
                  uint32_t oracle_delivery,  // 0 auto, 1 dense, 2 edge
                  uint32_t crash_cut, uint32_t recover_cut,  // SPEC §6c
                  uint32_t max_crashed,
                  uint32_t max_delay,        // SPEC §A.2 horizon (0 = off)
                  uint32_t net_switch,     // SPEC §9 switch model
                  uint32_t n_aggregators, uint32_t agg_fail_cut,
                  uint32_t agg_stale_cut, uint32_t agg_max_stale,
                  uint32_t agg_byz,        // SPEC §9b poisoned combines
                  uint32_t agg_poison_cut, uint32_t byz_uplink_cut,
                  uint32_t desync_cut,      // SPEC §B timer skew
                  uint32_t max_skew,        // skew depth bound [1, 8]
                  uint8_t* out_committed,   // [N*S]
                  uint32_t* out_dval,       // [N*S]
                  uint32_t* out_view) {     // [N]
  if (n_nodes != 3 * f + 1 || n_byzantine > f || oracle_delivery > 2 ||
      max_delay > 16 ||
      max_skew < 1 || max_skew > 8)
    return 1;
  if (!ctpu::valid_switch(net_switch, n_aggregators, n_nodes,
                          agg_fail_cut, agg_stale_cut, agg_max_stale) ||
      !ctpu::valid_poison(net_switch, n_aggregators, agg_byz,
                          agg_poison_cut, byz_uplink_cut))
    return 1;
  ctpu::PbftSim sim;
  sim.seed = seed; sim.N = n_nodes; sim.R = n_rounds; sim.S = n_slots;
  sim.f = f; sim.view_timeout = view_timeout; sim.n_byz = n_byzantine;
  sim.equiv = byz_equivocate;
  sim.fault_bcast = fault_bcast;
  sim.desync_cut = desync_cut; sim.max_skew = max_skew;
  sim.drop_cut = drop_cut; sim.part_cut = part_cut; sim.churn_cut = churn_cut;
  sim.delivery = oracle_delivery;
  sim.crash_cut = crash_cut; sim.recover_cut = recover_cut;
  sim.max_crashed = max_crashed; sim.max_delay = max_delay;
  sim.net_switch = net_switch; sim.n_agg = n_aggregators;
  sim.agg_fail_cut = agg_fail_cut; sim.agg_stale_cut = agg_stale_cut;
  sim.agg_max_stale = agg_max_stale;
  sim.agg.agg_byz = agg_byz;           // SPEC §9b
  sim.agg.poison_cut = agg_poison_cut;
  sim.agg.uplink_cut = byz_uplink_cut;
  sim.run();
  size_t ns = size_t(n_nodes) * n_slots;
  std::memcpy(out_committed, sim.committed.data(), ns);
  std::memcpy(out_dval, sim.dval.data(), sizeof(uint32_t) * ns);
  std::memcpy(out_view, sim.view.data(), sizeof(uint32_t) * n_nodes);
  return 0;
}

int ctpu_paxos_run(uint64_t seed, uint32_t n_nodes, uint32_t n_rounds,
                   uint32_t n_slots, uint32_t n_proposers,
                   uint32_t drop_cut, uint32_t part_cut, uint32_t churn_cut,
                   uint32_t oracle_delivery,    // 0 auto, 1 dense, 2 edge
                   uint32_t crash_cut, uint32_t recover_cut,  // SPEC §6c
                   uint32_t max_crashed,
                   uint32_t max_delay,          // SPEC §A.2 (0 = off)
                   uint32_t net_switch,     // SPEC §9 switch model
                   uint32_t n_aggregators, uint32_t agg_fail_cut,
                   uint32_t agg_stale_cut, uint32_t agg_max_stale,
                   uint32_t* out_learned_val,   // [N*S]
                   uint8_t* out_learned_mask,   // [N*S]
                   uint32_t* out_promised,      // [N*S]
                   uint32_t* out_acc_bal,       // [N*S]
                   uint32_t* out_acc_val) {     // [N*S]
  if (n_nodes == 0 || n_slots == 0 || oracle_delivery > 2 || max_delay > 16)
    return 1;
  if (!ctpu::valid_switch(net_switch, n_aggregators, n_nodes,
                          agg_fail_cut, agg_stale_cut, agg_max_stale))
    return 1;
  ctpu::PaxosSim sim;
  sim.seed = seed; sim.N = n_nodes; sim.R = n_rounds; sim.S = n_slots;
  sim.P = n_proposers ? n_proposers : n_nodes;
  sim.drop_cut = drop_cut; sim.part_cut = part_cut; sim.churn_cut = churn_cut;
  sim.delivery = oracle_delivery;
  sim.crash_cut = crash_cut; sim.recover_cut = recover_cut;
  sim.max_crashed = max_crashed; sim.max_delay = max_delay;
  sim.net_switch = net_switch; sim.n_agg = n_aggregators;
  sim.agg_fail_cut = agg_fail_cut; sim.agg_stale_cut = agg_stale_cut;
  sim.agg_max_stale = agg_max_stale;
  sim.run();
  size_t ns = size_t(n_nodes) * n_slots;
  std::memcpy(out_learned_val, sim.learned_val.data(), sizeof(uint32_t) * ns);
  std::memcpy(out_learned_mask, sim.learned_mask.data(), ns);
  std::memcpy(out_promised, sim.promised.data(), sizeof(uint32_t) * ns);
  std::memcpy(out_acc_bal, sim.acc_bal.data(), sizeof(uint32_t) * ns);
  std::memcpy(out_acc_val, sim.acc_val.data(), sizeof(uint32_t) * ns);
  return 0;
}

int ctpu_dpos_run(uint64_t seed, uint32_t n_nodes, uint32_t n_rounds,
                  uint32_t log_capacity, uint32_t n_candidates,
                  uint32_t n_producers, uint32_t epoch_len,
                  uint32_t drop_cut, uint32_t part_cut, uint32_t churn_cut,
                  uint32_t crash_cut, uint32_t recover_cut,  // SPEC §6c
                  uint32_t max_crashed,
                  uint32_t miss_cut,        // SPEC §A.1 slot-miss cutoff
                  uint32_t max_delay,       // SPEC §A.2 horizon (0 = off)
                  uint32_t suppress_cut,    // SPEC §A.4 correlated outages
                  uint32_t suppress_window,
                  uint32_t* out_chain_r,    // [V*L]
                  uint32_t* out_chain_p,    // [V*L]
                  uint32_t* out_chain_len,  // [V]
                  int32_t* out_lib) {       // [V] SPEC §7 LIB, -1 = none
  if (n_nodes == 0 || n_candidates == 0 || n_producers == 0 ||
      n_producers > n_candidates || n_candidates > n_nodes ||
      epoch_len == 0 || max_delay > 16 || suppress_window == 0)
    return 1;
  ctpu::DposSim sim;
  sim.seed = seed; sim.V = n_nodes; sim.R = n_rounds; sim.L = log_capacity;
  sim.C = n_candidates; sim.K = n_producers; sim.epoch_len = epoch_len;
  sim.drop_cut = drop_cut; sim.part_cut = part_cut; sim.churn_cut = churn_cut;
  sim.crash_cut = crash_cut; sim.recover_cut = recover_cut;
  sim.max_crashed = max_crashed;
  sim.miss_cut = miss_cut; sim.max_delay = max_delay;
  sim.suppress_cut = suppress_cut; sim.suppress_window = suppress_window;
  sim.run();
  size_t vl = size_t(n_nodes) * log_capacity;
  std::memcpy(out_chain_r, sim.chain_r.data(), sizeof(uint32_t) * vl);
  std::memcpy(out_chain_p, sim.chain_p.data(), sizeof(uint32_t) * vl);
  std::memcpy(out_chain_len, sim.chain_len.data(), sizeof(uint32_t) * n_nodes);
  std::memcpy(out_lib, sim.lib.data(), sizeof(int32_t) * n_nodes);
  return 0;
}

int ctpu_hotstuff_run(uint64_t seed, uint32_t n_nodes, uint32_t n_rounds,
                      uint32_t n_slots, uint32_t f, uint32_t view_timeout,
                      uint32_t n_byzantine,  // SPEC §7b byzantine minority
                      uint32_t byz_equivocate,  // SPEC §7c fork model
                      uint32_t drop_cut, uint32_t part_cut,
                      uint32_t churn_cut,
                      uint32_t crash_cut, uint32_t recover_cut,  // SPEC §6c
                      uint32_t max_crashed,
                      uint32_t max_delay,       // SPEC §A.2 (0 = off)
                      uint32_t net_switch,     // SPEC §9 switch model
                      uint32_t n_aggregators, uint32_t agg_fail_cut,
                      uint32_t agg_stale_cut, uint32_t agg_max_stale,
                      uint32_t agg_byz,        // SPEC §9b poisoned combines
                      uint32_t agg_poison_cut, uint32_t byz_uplink_cut,
                      uint32_t desync_cut,      // SPEC §B timer skew
                      uint32_t max_skew,        // skew depth bound [1, 8]
                      uint8_t* out_committed,   // [N*S]
                      uint32_t* out_dval,       // [N*S]
                      uint32_t* out_clen,       // [N]
                      uint32_t* out_view) {     // [N]
  if (n_nodes != 3 * f + 1 || n_byzantine > f || max_delay > 16 ||
      max_skew < 1 || max_skew > 8)
    return 1;
  if (!ctpu::valid_switch(net_switch, n_aggregators, n_nodes,
                          agg_fail_cut, agg_stale_cut, agg_max_stale) ||
      !ctpu::valid_poison(net_switch, n_aggregators, agg_byz,
                          agg_poison_cut, byz_uplink_cut))
    return 1;
  ctpu::HotstuffSim sim;
  sim.seed = seed; sim.N = n_nodes; sim.R = n_rounds; sim.S = n_slots;
  sim.f = f; sim.view_timeout = view_timeout; sim.n_byz = n_byzantine;
  sim.equiv = byz_equivocate;
  sim.drop_cut = drop_cut; sim.part_cut = part_cut; sim.churn_cut = churn_cut;
  sim.desync_cut = desync_cut; sim.max_skew = max_skew;
  sim.crash_cut = crash_cut; sim.recover_cut = recover_cut;
  sim.max_crashed = max_crashed; sim.max_delay = max_delay;
  sim.net_switch = net_switch; sim.n_agg = n_aggregators;
  sim.agg_fail_cut = agg_fail_cut; sim.agg_stale_cut = agg_stale_cut;
  sim.agg_max_stale = agg_max_stale;
  sim.agg.agg_byz = agg_byz;           // SPEC §9b
  sim.agg.poison_cut = agg_poison_cut;
  sim.agg.uplink_cut = byz_uplink_cut;
  sim.run();
  size_t ns = size_t(n_nodes) * n_slots;
  std::memcpy(out_committed, sim.committed.data(), ns);
  std::memcpy(out_dval, sim.dval.data(), sizeof(uint32_t) * ns);
  std::memcpy(out_clen, sim.clen.data(), sizeof(uint32_t) * n_nodes);
  std::memcpy(out_view, sim.view_.data(), sizeof(uint32_t) * n_nodes);
  return 0;
}

// Threefry probe for cross-language RNG parity tests.
uint32_t ctpu_random_u32(uint64_t seed, uint32_t stream, uint32_t ctx,
                         uint32_t c0, uint32_t c1) {
  return ctpu::random_u32(seed, stream, ctx, c0, c1);
}

// Delivery-mixer probe (SPEC §2) for cross-language RNG parity tests.
uint32_t ctpu_delivery_u32(uint64_t seed, uint32_t r, uint32_t i, uint32_t j) {
  return ctpu::delivery_u32(seed, r, i, j);
}

}  // extern "C"
