// C++ scalar oracle — the CPU reference engine of the framework.
//
// Plays the role the Rust implementation plays in the reference
// (`2892931976/consensus-rs`, SURVEY.md §2 components 1-12): a sequential,
// per-node implementation of each consensus protocol against which the
// batched JAX/TPU engine is checked for decided-log BYTE-equivalence
// (BASELINE.json:2,5). Implements docs/SPEC.md exactly — every phase,
// tie-break, and threefry draw. Exposed to Python via a C ABI (ctypes;
// pybind11 is not available in this environment).
//
// Build: `make -C cpp` → liboracle.so.

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <vector>

#include "threefry.h"

namespace ctpu {
namespace {

constexpr uint32_t ROLE_F = 0, ROLE_C = 1, ROLE_L = 2;
constexpr int32_t NONE = -1;

// Per-round delivery decisions (SPEC §2), materialized once per round —
// each directed edge is queried up to ~7 times per round across the phases,
// so recomputing the 20-round threefry per query would distort the
// single-core baseline this oracle exists to provide (BASELINE.md).
struct Net {
  uint32_t n = 0;
  std::vector<uint8_t> mat;  // [n*n] delivered?

  void begin_round(uint64_t seed, uint32_t n_, uint32_t r, uint32_t drop_cut,
                   uint32_t part_cut) {
    n = n_;
    mat.assign(size_t(n) * n, 0);
    const bool part_active =
        random_u32(seed, STREAM_PARTITION, r, 0, 0) < part_cut;
    std::vector<uint8_t> side(n, 0);
    if (part_active)
      for (uint32_t i = 0; i < n; ++i)
        side[i] = random_u32(seed, STREAM_PARTITION, r, 1, i) & 1u;
    for (uint32_t i = 0; i < n; ++i)
      for (uint32_t j = 0; j < n; ++j) {
        if (i == j) continue;
        if (random_u32(seed, STREAM_DELIVER, r, i, j) < drop_cut) continue;
        if (part_active && side[i] != side[j]) continue;
        mat[size_t(i) * n + j] = 1;
      }
  }
  bool delivered(uint32_t i, uint32_t j) const {
    return mat[size_t(i) * n + j] != 0;
  }
};

inline bool churn_fires(uint64_t seed, uint32_t r, uint32_t cut) {
  return random_u32(seed, STREAM_CHURN, r, 0, 0) < cut;
}

// ---------------------------------------------------------------------------
// Raft (SPEC §3).
// ---------------------------------------------------------------------------

struct RaftSim {
  uint64_t seed;
  uint32_t N, R, L, E, t_min, t_max;
  uint32_t drop_cut, part_cut, churn_cut;

  // State, struct-of-arrays to mirror the array schema (SURVEY.md §7).
  std::vector<uint32_t> term, role, log_len, commit, timer, timeout;
  std::vector<int32_t> voted_for;
  std::vector<uint32_t> log_term, log_val;        // [N*L]
  std::vector<uint32_t> match_idx, next_idx;      // [N*N]
  Net net;

  uint32_t& lt(uint32_t i, uint32_t k) { return log_term[i * L + k]; }
  uint32_t& lv(uint32_t i, uint32_t k) { return log_val[i * L + k]; }
  uint32_t& mi(uint32_t l, uint32_t j) { return match_idx[l * N + j]; }
  uint32_t& ni(uint32_t l, uint32_t j) { return next_idx[l * N + j]; }

  uint32_t draw_timeout(uint32_t t, uint32_t i) const {
    return t_min + random_u32(seed, STREAM_TIMEOUT, t, 0, i) % (t_max - t_min);
  }

  // SPEC §3 term-change rule (non-candidacy causes).
  void bump_term(uint32_t i, uint32_t T) {
    term[i] = T;
    role[i] = ROLE_F;
    voted_for[i] = NONE;
    timeout[i] = draw_timeout(T, i);
  }

  void init() {
    term.assign(N, 0); role.assign(N, ROLE_F); log_len.assign(N, 0);
    commit.assign(N, 0); timer.assign(N, 0); voted_for.assign(N, NONE);
    timeout.resize(N);
    log_term.assign(size_t(N) * L, 0); log_val.assign(size_t(N) * L, 0);
    match_idx.assign(size_t(N) * N, 0); next_idx.assign(size_t(N) * N, 1);
    for (uint32_t i = 0; i < N; ++i) timeout[i] = draw_timeout(0, i);
  }

  void round(uint32_t r) {
    const uint32_t majority = N / 2 + 1;
    net.begin_round(seed, N, r, drop_cut, part_cut);
    std::vector<uint8_t> reset(N, 0);

    // ---- P0 churn: all leaders step down.
    if (churn_fires(seed, r, churn_cut))
      for (uint32_t i = 0; i < N; ++i)
        if (role[i] == ROLE_L) { role[i] = ROLE_F; timer[i] = 0; reset[i] = 1; }

    // ---- P1 candidacy.
    for (uint32_t i = 0; i < N; ++i)
      if (role[i] != ROLE_L && timer[i] >= timeout[i]) {
        term[i] += 1;
        role[i] = ROLE_C;
        voted_for[i] = int32_t(i);
        timer[i] = 0; reset[i] = 1;
        timeout[i] = draw_timeout(term[i], i);
      }

    // ---- P2 election. Snapshot requests (post-P1 sender state).
    std::vector<uint8_t> was_cand(N);
    std::vector<uint32_t> req_term(N), req_lidx(N), req_lterm(N);
    for (uint32_t c = 0; c < N; ++c) {
      was_cand[c] = role[c] == ROLE_C;
      req_term[c] = term[c];
      req_lidx[c] = log_len[c];
      req_lterm[c] = log_len[c] ? lt(c, log_len[c] - 1) : 0;
    }
    // P2a: term catch-up from delivered requests.
    for (uint32_t j = 0; j < N; ++j) {
      uint32_t T = term[j];
      for (uint32_t c = 0; c < N; ++c)
        if (was_cand[c] && net.delivered(c, j)) T = std::max(T, req_term[c]);
      if (T > term[j]) bump_term(j, T);
    }
    // P2b: grants.
    std::vector<int32_t> grant(N, NONE);
    for (uint32_t j = 0; j < N; ++j) {
      uint32_t own_lterm = log_len[j] ? lt(j, log_len[j] - 1) : 0;
      int32_t g = NONE;
      auto eligible = [&](uint32_t c) {
        if (!was_cand[c] || c == j || !net.delivered(c, j)) return false;
        if (req_term[c] != term[j]) return false;
        return req_lterm[c] > own_lterm ||
               (req_lterm[c] == own_lterm && req_lidx[c] >= log_len[j]);
      };
      if (voted_for[j] != NONE) {
        if (eligible(uint32_t(voted_for[j]))) g = voted_for[j];  // re-grant
      } else {
        for (uint32_t c = 0; c < N; ++c)
          if (eligible(c)) { g = int32_t(c); break; }  // lowest id
      }
      if (g != NONE) { voted_for[j] = g; timer[j] = 0; reset[j] = 1; }
      grant[j] = g;
    }
    // P2c: tally; winners become leaders.
    for (uint32_t c = 0; c < N; ++c) {
      if (role[c] != ROLE_C) continue;  // may have been bumped in P2a
      uint32_t votes = 1;  // self
      for (uint32_t j = 0; j < N; ++j)
        if (j != c && grant[j] == int32_t(c) && net.delivered(j, c)) ++votes;
      if (votes >= majority) {
        role[c] = ROLE_L;
        timer[c] = 0; reset[c] = 1;
        for (uint32_t j = 0; j < N; ++j) { mi(c, j) = 0; ni(c, j) = log_len[c] + 1; }
        mi(c, c) = log_len[c];
      }
    }

    // ---- P3 replication.
    // (a) propose.
    for (uint32_t l = 0; l < N; ++l)
      if (role[l] == ROLE_L && log_len[l] < E && log_len[l] < L) {
        lt(l, log_len[l]) = term[l];
        lv(l, log_len[l]) = random_u32(seed, STREAM_VALUE, r, 0, l);
        log_len[l] += 1;
        mi(l, l) = log_len[l];
      }
    // (b) snapshot sender state (post-(a), commit pre-(e)).
    std::vector<uint8_t> was_leader(N);
    std::vector<uint32_t> s_term(N), s_len(N), s_commit(N);
    std::vector<uint32_t> s_next;  // [N*N] snapshot of next_idx
    s_next = next_idx;
    std::vector<uint32_t> s_logt = log_term, s_logv = log_val;
    for (uint32_t l = 0; l < N; ++l) {
      was_leader[l] = role[l] == ROLE_L;
      s_term[l] = term[l]; s_len[l] = log_len[l]; s_commit[l] = commit[l];
    }
    // (c) receivers.
    std::vector<int32_t> ack_to(N, NONE);
    std::vector<uint8_t> ack_ok(N, 0);
    std::vector<uint32_t> ack_match(N, 0), ack_term(N, 0);
    for (uint32_t j = 0; j < N; ++j) {
      uint32_t T = term[j];
      for (uint32_t l = 0; l < N; ++l)
        if (was_leader[l] && net.delivered(l, j)) T = std::max(T, s_term[l]);
      if (T > term[j]) bump_term(j, T);
      int32_t lstar = NONE;
      for (uint32_t l = 0; l < N; ++l)
        if (was_leader[l] && l != j && net.delivered(l, j) && s_term[l] == term[j]) {
          lstar = int32_t(l);
          break;  // lowest id
        }
      if (lstar == NONE) continue;
      uint32_t l = uint32_t(lstar);
      timer[j] = 0; reset[j] = 1;
      if (role[j] == ROLE_C) role[j] = ROLE_F;
      uint32_t prev = s_next[l * N + j] - 1;
      uint32_t prev_term = prev ? s_logt[size_t(l) * L + prev - 1] : 0;
      bool ok = prev == 0 ||
                (prev <= log_len[j] && lt(j, prev - 1) == prev_term);
      ack_to[j] = lstar;
      ack_term[j] = term[j];
      if (ok) {
        for (uint32_t k = prev; k < s_len[l]; ++k) {
          lt(j, k) = s_logt[size_t(l) * L + k];
          lv(j, k) = s_logv[size_t(l) * L + k];
        }
        log_len[j] = s_len[l];
        commit[j] = std::max(commit[j], std::min(s_commit[l], log_len[j]));
        ack_ok[j] = 1;
        ack_match[j] = s_len[l];
      }
    }
    // (d) leaders process acks (only if still leader after (c)).
    for (uint32_t l = 0; l < N; ++l) {
      if (!was_leader[l] || role[l] != ROLE_L) continue;
      uint32_t T = term[l];
      for (uint32_t j = 0; j < N; ++j)
        if (ack_to[j] == int32_t(l) && net.delivered(j, l))
          T = std::max(T, ack_term[j]);
      if (T > term[l]) { bump_term(l, T); continue; }
      for (uint32_t j = 0; j < N; ++j) {
        if (ack_to[j] != int32_t(l) || !net.delivered(j, l)) continue;
        if (ack_ok[j]) {
          mi(l, j) = std::max(mi(l, j), ack_match[j]);
          ni(l, j) = mi(l, j) + 1;
        } else {
          ni(l, j) = std::max(1u, ni(l, j) - 1);
        }
      }
      // (e) commit advance.
      std::vector<uint32_t> m(match_idx.begin() + size_t(l) * N,
                              match_idx.begin() + size_t(l) * N + N);
      std::nth_element(m.begin(), m.begin() + (majority - 1), m.end(),
                       std::greater<uint32_t>());
      uint32_t med = m[majority - 1];
      if (med > commit[l] && med > 0 && lt(l, med - 1) == term[l])
        commit[l] = med;
    }

    // ---- P4 timers.
    for (uint32_t i = 0; i < N; ++i) {
      if (role[i] == ROLE_L) timer[i] = 0;
      else if (!reset[i]) timer[i] += 1;
    }
  }

  void run() {
    init();
    for (uint32_t r = 0; r < R; ++r) round(r);
  }
};

}  // namespace
}  // namespace ctpu

// ---------------------------------------------------------------------------
// C ABI (ctypes). One call runs one sweep; Python loops sweeps with
// seed_b = base_seed + b (SPEC §1) and serializes via core/serialize.py.
// ---------------------------------------------------------------------------

extern "C" {

int ctpu_raft_run(uint64_t seed, uint32_t n_nodes, uint32_t n_rounds,
                  uint32_t log_capacity, uint32_t max_entries,
                  uint32_t t_min, uint32_t t_max,
                  uint32_t drop_cut, uint32_t part_cut, uint32_t churn_cut,
                  uint32_t* out_commit,    // [N]
                  uint32_t* out_log_term,  // [N*L]
                  uint32_t* out_log_val,   // [N*L]
                  uint32_t* out_term,      // [N]
                  uint32_t* out_role) {    // [N]
  if (n_nodes == 0 || t_max <= t_min) return 1;
  ctpu::RaftSim sim;
  sim.seed = seed; sim.N = n_nodes; sim.R = n_rounds; sim.L = log_capacity;
  sim.E = max_entries; sim.t_min = t_min; sim.t_max = t_max;
  sim.drop_cut = drop_cut; sim.part_cut = part_cut; sim.churn_cut = churn_cut;
  sim.run();
  std::memcpy(out_commit, sim.commit.data(), sizeof(uint32_t) * n_nodes);
  std::memcpy(out_log_term, sim.log_term.data(),
              sizeof(uint32_t) * size_t(n_nodes) * log_capacity);
  std::memcpy(out_log_val, sim.log_val.data(),
              sizeof(uint32_t) * size_t(n_nodes) * log_capacity);
  std::memcpy(out_term, sim.term.data(), sizeof(uint32_t) * n_nodes);
  std::memcpy(out_role, sim.role.data(), sizeof(uint32_t) * n_nodes);
  return 0;
}

// Threefry probe for cross-language RNG parity tests.
uint32_t ctpu_random_u32(uint64_t seed, uint32_t stream, uint32_t ctx,
                         uint32_t c0, uint32_t c1) {
  return ctpu::random_u32(seed, stream, ctx, c0, c1);
}

}  // extern "C"
