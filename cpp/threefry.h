// Threefry-2x32 (20 rounds) — scalar twin of consensus_tpu/core/rng.py.
// The C++ oracle and the JAX engine must draw IDENTICAL random streams for
// decided-log byte-equivalence (BASELINE.json:2,5); both implement the
// Random123 Threefry-2x32 schedule and the same (seed^stream, ctx)/(hi,lo)
// key/counter discipline. Validated against the Python twin in
// tests/test_oracle_bindings.py.
#pragma once
#include <cstdint>

namespace ctpu {

// Stream constants — must match consensus_tpu/core/rng.py.
constexpr uint32_t STREAM_DELIVER   = 0x9E3779B1u;
constexpr uint32_t STREAM_TIMEOUT   = 0x85EBCA77u;
constexpr uint32_t STREAM_CHURN     = 0xC2B2AE3Du;
constexpr uint32_t STREAM_PARTITION = 0x27D4EB2Fu;
constexpr uint32_t STREAM_STAKE     = 0x165667B1u;
constexpr uint32_t STREAM_VOTE      = 0xD3A2646Cu;
constexpr uint32_t STREAM_VALUE     = 0xFD7046C5u;
constexpr uint32_t STREAM_BYZANTINE = 0xB55A4F09u;
constexpr uint32_t STREAM_EQUIV     = 0x94D049BBu;
constexpr uint32_t STREAM_CRASH     = 0x68E31DA5u;  // SPEC §6c (mirrored)
constexpr uint32_t STREAM_SLOTMISS  = 0x7F4A7C15u;  // SPEC §A.1 DPoS slot miss
constexpr uint32_t STREAM_DELAY     = 0x2545F491u;  // SPEC §A.2 retransmit
constexpr uint32_t STREAM_AGG       = 0x510E527Fu;  // SPEC §9 aggregator faults
constexpr uint32_t STREAM_POISON    = 0x6A09E667u;  // SPEC §9b poisoned combines
constexpr uint32_t STREAM_SUPPRESS  = 0x1F83D9ABu;  // SPEC §A.4 producer runs
constexpr uint32_t STREAM_DESYNC    = 0x5BE0CD19u;  // SPEC §B view-timer skew

inline uint32_t rotl32(uint32_t x, int r) {
  return (x << r) | (x >> (32 - r));
}

struct U32x2 { uint32_t v0, v1; };

inline U32x2 threefry2x32(uint32_t k0, uint32_t k1, uint32_t c0, uint32_t c1) {
  constexpr uint32_t KS_PARITY = 0x1BD11BDAu;
  constexpr int ROT_A[4] = {13, 15, 26, 6};
  constexpr int ROT_B[4] = {17, 29, 16, 24};
  uint32_t ks[3] = {k0, k1, k0 ^ k1 ^ KS_PARITY};
  uint32_t x0 = c0 + ks[0];
  uint32_t x1 = c1 + ks[1];
  for (int block = 0; block < 5; ++block) {
    const int* rots = (block % 2 == 0) ? ROT_A : ROT_B;
    for (int i = 0; i < 4; ++i) {
      x0 += x1;
      x1 = rotl32(x1, rots[i]) ^ x0;
    }
    x0 += ks[(block + 1) % 3];
    x1 += ks[(block + 2) % 3] + static_cast<uint32_t>(block + 1);
  }
  return {x0, x1};
}

// Draw one u32 word: key=(lo32(seed)^stream, ctx), ctr=(c0, c1).
// See docs/SPEC.md §1 for the stream table.
inline uint32_t random_u32(uint64_t seed, uint32_t stream, uint32_t ctx,
                           uint32_t c0, uint32_t c1) {
  uint32_t k0 = static_cast<uint32_t>(seed & 0xFFFFFFFFull) ^ stream;
  return threefry2x32(k0, ctx, c0, c1).v0;
}

// --- SPEC §2 delivery mixer (MurmurHash3-style absorb/finalize) -----------
// The per-edge delivery drop draw is N^2 per round — the one stream hot
// enough that the 20-round threefry schedule dominates the TPU kernel
// (benchmarks/profile_raft.py). Scalar twin of core/rng.py
// delivery_u32_np; cross-validated in tests/test_oracle_bindings.py.
inline uint32_t mix_absorb(uint32_t h, uint32_t c) {
  uint32_t k = c * 0xCC9E2D51u;
  k = rotl32(k, 15) * 0x1B873593u;
  h = rotl32(h ^ k, 13);
  return h * 5u + 0xE6546B64u;
}

inline uint32_t mix_fin(uint32_t h) {
  h ^= h >> 16;
  h *= 0x85EBCA6Bu;
  h ^= h >> 13;
  h *= 0xC2B2AE35u;
  return h ^ (h >> 16);
}

// delivery_u32(seed, r, i, j) — the SPEC §2 drop draw for edge i->j.
// Callers looping over edges should hoist the (seed, r) and i absorbs.
inline uint32_t delivery_u32(uint64_t seed, uint32_t r, uint32_t i,
                             uint32_t j) {
  uint32_t k0 = static_cast<uint32_t>(seed & 0xFFFFFFFFull) ^ STREAM_DELIVER;
  return mix_fin(mix_absorb(mix_absorb(mix_absorb(k0, r), i), j));
}

// delay_u32(seed, q, d, i, j) — the SPEC §A.2 delayed-retransmission
// draw for origin round q, delay d, edge i->j (same mixer, STREAM_DELAY
// key, FOUR absorbs). Scalar twin of core/rng.py delay_u32_np.
inline uint32_t delay_u32(uint64_t seed, uint32_t q, uint32_t d, uint32_t i,
                          uint32_t j) {
  uint32_t k0 = static_cast<uint32_t>(seed & 0xFFFFFFFFull) ^ STREAM_DELAY;
  return mix_fin(mix_absorb(mix_absorb(mix_absorb(mix_absorb(k0, q), d), i),
                            j));
}

// SPEC §A.2 delayed-openness OR-term: does a flight dropped at some
// round q in [r - max_delay, r) arrive at r via a successful
// retransmission? Pure function of (seed, r, edge) — no queue state.
inline bool delayed_open(uint64_t seed, uint32_t r, uint32_t i, uint32_t j,
                         uint32_t drop_cut, uint32_t max_delay) {
  for (uint32_t d = 1; d <= max_delay && d <= r; ++d) {
    const uint32_t q = r - d;
    if (delivery_u32(seed, q, i, j) < drop_cut &&
        delay_u32(seed, q, d, i, j) >= drop_cut)
      return true;
  }
  return false;
}

}  // namespace ctpu
