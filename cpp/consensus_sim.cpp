// consensus-sim — the native CLI driver (SURVEY.md §2 component 13).
//
// Plays the role of the reference's CLI binary: flags → Config → run →
// JSON report. The CPU engine is the in-process C++ oracle (oracle.cpp);
// `--engine tpu` re-execs `python3 -m consensus_tpu` with the same flags
// so one front door drives both engines, mirroring the reference's
// engine-pluggable `Consensus` trait seam (BASELINE.json:5).
//
// The JSON report contains the SHA-256 digest of the canonical decided-log
// serialization (docs/SPEC.md §4) — byte-identical to the Python side's
// `RunResult.digest`, so cross-engine equivalence is a string compare:
//
//   ./consensus-sim --protocol raft --nodes 5 --rounds 64 | jq .digest
//   ./consensus-sim --engine tpu  --protocol raft ...     | jq .digest

#include <cinttypes>
#include <climits>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <memory>
#include <string>
#include <vector>

#include <unistd.h>

#include "engine.h"
#include "sha256.h"

namespace {

struct Args {
  std::string protocol = "raft";
  std::string engine = "cpu";
  uint32_t nodes = 5, rounds = 64, sweeps = 1;
  uint64_t seed = 0;
  uint32_t log_capacity = 128, max_entries = 100;
  uint32_t t_min = 3, t_max = 8;
  uint32_t max_active = 0;  // raft: 0 = dense, >0 = SPEC §3b active cap
  double drop_rate = 0.0, partition_rate = 0.0, churn_rate = 0.0;
  // SPEC §6c crash-recover adversary (mirrored in oracle.cpp).
  double crash_prob = 0.0, recover_prob = 0.0;
  uint32_t max_crashed = 0;
  // SPEC §A.1 per-producer DPoS slot faults / §A.2 bounded delay.
  double miss_rate = 0.0;
  uint32_t max_delay_rounds = 0;
  // SPEC §A.4 correlated DPoS producer suppression (window-keyed).
  double suppress_rate = 0.0;
  uint32_t suppress_window = 16;
  // SPEC §9 in-network vote aggregation (mirrored in oracle.cpp AggNet).
  std::string net_model = "flat";  // "flat" | "switch"
  uint32_t n_aggregators = 0;
  double agg_fail_rate = 0.0, agg_stale_rate = 0.0;
  uint32_t agg_max_stale = 1;
  // SPEC §9b poisoned aggregation (pbft/hotstuff switch models only).
  uint32_t agg_byz = 0;
  double agg_poison_rate = 0.0, byz_uplink_rate = 0.0;
  // SPEC §B per-node view-synchronizer timer skew (pbft/hotstuff).
  double desync_rate = 0.0;
  uint32_t max_skew_rounds = 1;
  uint32_t f = 1, view_timeout = 8, n_byzantine = 0;
  std::string byz_mode = "silent";
  std::string fault_model = "edge";  // "edge" (SPEC §2) | "bcast" (§6b, pbft)
  // Oracle delivery strategy (execution only, digests unchanged):
  // "auto" (per-engine choice), "dense" ([N,N] materialization), or
  // "edge" (on-demand edge queries — the cross-check knob).
  std::string oracle_delivery = "auto";
  uint32_t n_proposers = 0;
  uint32_t n_candidates = 16, n_producers = 4, epoch_len = 16;
  std::string out_path;  // optional: dump raw payload bytes
  // SPEC Appendix A scripted scenario name. Scenario runs pair the
  // attack config with flight-recorder timeline assertions, which only
  // the TPU engine records — `--engine tpu --scenario NAME` re-execs
  // the Python front door before strict parsing; a cpu-engine scenario
  // is rejected below rather than silently ignored.
  std::string scenario;
  // --serve-port: live /metrics + /status introspection, served by the
  // Python process's metrics registry — `--engine tpu --serve-port P`
  // re-execs the Python front door before strict parsing; the scalar
  // oracle has no registry to serve, so a cpu-engine request is
  // rejected below rather than silently ignored.
  int serve_port = 0;
  bool serve_port_given = false;  // -1 must not double as "absent"
  bool nodes_given = false;
};

// Must equal consensus_tpu.core.rng.prob_threshold_u32 — both engines
// compare raw u32 draws against the same integer cutoffs.
uint32_t prob_threshold_u32(double p) {
  if (p <= 0.0) return 0;
  if (p >= 1.0) return 0xFFFFFFFFu;
  double v = p * 4294967296.0;
  uint64_t c = uint64_t(v);
  return c > 0xFFFFFFFFull ? 0xFFFFFFFFu : uint32_t(c);
}

[[noreturn]] void usage(const char* argv0, int code) {
  std::fprintf(
      stderr,
      "usage: %s [--protocol raft|pbft|paxos|dpos|hotstuff] [--engine cpu|tpu]\n"
      "  [--nodes N] [--rounds R] [--sweeps B] [--seed S]\n"
      "  [--log-capacity L] [--max-entries E] [--t-min T] [--t-max T]\n"
      "  [--max-active A]   (raft: 0 = dense, >0 = SPEC 3b active cap)\n"
      "  [--drop-rate P] [--partition-rate P] [--churn-rate P]\n"
      "  [--crash-prob P] [--recover-prob P] [--max-crashed K]  (SPEC 6c)\n"
      "  [--miss-rate P]           (SPEC A.1 per-producer slot miss; dpos)\n"
      "  [--suppress-rate P] [--suppress-window W]  (SPEC A.4; dpos)\n"
      "  [--max-delay-rounds D]    (SPEC A.2 bounded delay, D <= 16)\n"
      "  [--net-model flat|switch] [--n-aggregators K]   (SPEC 9)\n"
      "  [--agg-fail-rate P] [--agg-stale-rate P] [--agg-max-stale D]\n"
      "  [--agg-byz K] [--agg-poison-rate P] [--byz-uplink-rate P] (SPEC 9b)\n"
      "  [--desync-rate P] [--max-skew-rounds K] (SPEC B; pbft,hotstuff)\n"
      "  [--f F] [--view-timeout T] [--n-byzantine K]\n"
      "  [--byz-mode silent|equivocate] [--fault-model edge|bcast]\n"
      "  [--oracle-delivery auto|dense|edge]  (cpu engine; digests equal)\n"
      "  [--n-proposers P]\n"
      "  [--candidates C] [--producers K] [--epoch-len E] [--out FILE]\n"
      "  [--scenario NAME]   (scripted attack + timeline assertions; tpu)\n"
      "  [--serve-port P]    (live /metrics + /status introspection; tpu)\n",
      argv0);
  std::exit(code);
}

Args parse(int argc, char** argv) {
  Args a;
  for (int i = 1; i < argc; ++i) {
    std::string k = argv[i];
    auto need = [&](const char* name) -> const char* {
      if (i + 1 >= argc) {
        std::fprintf(stderr, "missing value for %s\n", name);
        std::exit(2);
      }
      return argv[++i];
    };
    if (k == "--protocol") a.protocol = need(k.c_str());
    else if (k == "--engine") a.engine = need(k.c_str());
    else if (k == "--nodes") { a.nodes = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10)); a.nodes_given = true; }
    else if (k == "--rounds") a.rounds = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--sweeps") a.sweeps = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--seed") a.seed = std::strtoull(need(k.c_str()), nullptr, 10);
    else if (k == "--log-capacity") a.log_capacity = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--max-entries") a.max_entries = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--t-min") a.t_min = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--t-max") a.t_max = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--max-active") a.max_active = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--drop-rate") a.drop_rate = std::strtod(need(k.c_str()), nullptr);
    else if (k == "--partition-rate") a.partition_rate = std::strtod(need(k.c_str()), nullptr);
    else if (k == "--churn-rate") a.churn_rate = std::strtod(need(k.c_str()), nullptr);
    else if (k == "--crash-prob") a.crash_prob = std::strtod(need(k.c_str()), nullptr);
    else if (k == "--recover-prob") a.recover_prob = std::strtod(need(k.c_str()), nullptr);
    else if (k == "--max-crashed") a.max_crashed = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--miss-rate") a.miss_rate = std::strtod(need(k.c_str()), nullptr);
    else if (k == "--suppress-rate") a.suppress_rate = std::strtod(need(k.c_str()), nullptr);
    else if (k == "--suppress-window") a.suppress_window = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--max-delay-rounds") a.max_delay_rounds = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--net-model") a.net_model = need(k.c_str());
    else if (k == "--n-aggregators") a.n_aggregators = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--agg-fail-rate") a.agg_fail_rate = std::strtod(need(k.c_str()), nullptr);
    else if (k == "--agg-stale-rate") a.agg_stale_rate = std::strtod(need(k.c_str()), nullptr);
    else if (k == "--agg-max-stale") a.agg_max_stale = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--agg-byz") a.agg_byz = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--agg-poison-rate") a.agg_poison_rate = std::strtod(need(k.c_str()), nullptr);
    else if (k == "--byz-uplink-rate") a.byz_uplink_rate = std::strtod(need(k.c_str()), nullptr);
    else if (k == "--desync-rate") a.desync_rate = std::strtod(need(k.c_str()), nullptr);
    else if (k == "--max-skew-rounds") a.max_skew_rounds = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--f") a.f = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--view-timeout") a.view_timeout = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--n-byzantine") a.n_byzantine = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--byz-mode") a.byz_mode = need(k.c_str());
    else if (k == "--fault-model") a.fault_model = need(k.c_str());
    else if (k == "--oracle-delivery") a.oracle_delivery = need(k.c_str());
    else if (k == "--n-proposers") a.n_proposers = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--candidates") a.n_candidates = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--producers") a.n_producers = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--epoch-len") a.epoch_len = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--out") a.out_path = need(k.c_str());
    else if (k == "--scenario") a.scenario = need(k.c_str());
    else if (k == "--serve-port") { a.serve_port = int(std::strtol(need(k.c_str()), nullptr, 10)); a.serve_port_given = true; }
    else if (k == "--help" || k == "-h") usage(argv[0], 0);
    else { std::fprintf(stderr, "unknown flag %s\n", k.c_str()); usage(argv[0], 2); }
  }
  if ((a.protocol == "pbft" || a.protocol == "hotstuff") && !a.nodes_given)
    a.nodes = 3 * a.f + 1;
  if (a.byz_mode != "silent" && a.byz_mode != "equivocate") {
    std::fprintf(stderr, "unknown --byz-mode %s\n", a.byz_mode.c_str());
    std::exit(2);
  }
  if (a.fault_model != "edge" && a.fault_model != "bcast") {
    std::fprintf(stderr, "unknown --fault-model %s\n", a.fault_model.c_str());
    std::exit(2);
  }
  if (a.fault_model == "bcast" && a.protocol != "pbft") {
    std::fprintf(stderr,
                 "--fault-model bcast (SPEC 6b) is a pbft model; %s would "
                 "silently ignore it\n", a.protocol.c_str());
    std::exit(2);
  }
  if (a.oracle_delivery != "auto" && a.oracle_delivery != "dense" &&
      a.oracle_delivery != "edge") {
    std::fprintf(stderr, "unknown --oracle-delivery %s\n",
                 a.oracle_delivery.c_str());
    std::exit(2);
  }
  if (!a.scenario.empty()) {
    std::fprintf(stderr,
                 "--scenario pairs a scripted attack config with "
                 "flight-recorder timeline assertions, which only the TPU "
                 "engine records — run with --engine tpu (this front door "
                 "re-execs the Python CLI for it)\n");
    std::exit(2);
  }
  if (a.serve_port_given) {
    std::fprintf(stderr,
                 "--serve-port serves the Python process's live metrics "
                 "registry (/metrics, /status); the scalar oracle records "
                 "none — run with --engine tpu (this front door re-execs "
                 "the Python CLI for it)\n");
    std::exit(2);
  }
  if (a.net_model != "flat" && a.net_model != "switch") {
    std::fprintf(stderr, "unknown --net-model %s\n", a.net_model.c_str());
    std::exit(2);
  }
  if (a.net_model == "switch") {
    if (a.protocol == "dpos") {
      std::fprintf(stderr,
                   "--net-model switch aggregates vote/quorum responses "
                   "(SPEC 9); dpos's producer row doesn't vote — the model "
                   "would be a silent no-op\n");
      std::exit(2);
    }
    if (a.n_aggregators < 1 || a.n_aggregators > a.nodes) {
      std::fprintf(stderr,
                   "--net-model switch needs 1 <= --n-aggregators <= "
                   "--nodes (SPEC 9)\n");
      std::exit(2);
    }
    if ((a.agg_byz != 0 || a.agg_poison_rate != 0.0 ||
         a.byz_uplink_rate != 0.0) &&
        a.protocol != "pbft" && a.protocol != "hotstuff") {
      std::fprintf(stderr,
                   "--agg-byz/--agg-poison-rate/--byz-uplink-rate poison "
                   "value-carrying combines (SPEC 9b) — a BFT-only model; "
                   "%s would silently ignore them\n", a.protocol.c_str());
      std::exit(2);
    }
    if (a.agg_byz > a.n_aggregators) {
      std::fprintf(stderr,
                   "--agg-byz must be <= --n-aggregators (SPEC 9b: the "
                   "byzantine aggregators are the last agg-byz vertex "
                   "ids)\n");
      std::exit(2);
    }
    if (a.agg_poison_rate > 0 && a.agg_byz == 0) {
      std::fprintf(stderr,
                   "--agg-poison-rate > 0 requires --agg-byz > 0 "
                   "(SPEC 9b)\n");
      std::exit(2);
    }
    if (a.byz_uplink_rate > 0 && a.n_byzantine == 0) {
      std::fprintf(stderr,
                   "--byz-uplink-rate > 0 requires --n-byzantine > 0 "
                   "(SPEC 9b: only byzantine replicas lie to their switch "
                   "uplink)\n");
      std::exit(2);
    }
  } else if (a.n_aggregators != 0 || a.agg_fail_rate != 0.0 ||
             a.agg_stale_rate != 0.0 || a.agg_max_stale != 1 ||
             a.agg_byz != 0 || a.agg_poison_rate != 0.0 ||
             a.byz_uplink_rate != 0.0) {
    std::fprintf(stderr,
                 "--n-aggregators/--agg-fail-rate/--agg-stale-rate/"
                 "--agg-max-stale/--agg-byz/--agg-poison-rate/"
                 "--byz-uplink-rate require --net-model switch (SPEC 9) — "
                 "they would be silently ignored\n");
    std::exit(2);
  }
  if (a.agg_max_stale < 1 || a.agg_max_stale > 8) {
    std::fprintf(stderr, "--agg-max-stale must be in [1, 8] (SPEC 9)\n");
    std::exit(2);
  }
  if (a.suppress_rate > 0 && a.protocol != "dpos") {
    std::fprintf(stderr,
                 "--suppress-rate (SPEC A.4) is a correlated DPoS "
                 "producer-suppression adversary; %s has no producer "
                 "schedule and would silently ignore it\n",
                 a.protocol.c_str());
    std::exit(2);
  }
  if (a.suppress_window < 1) {
    std::fprintf(stderr, "--suppress-window must be >= 1\n");
    std::exit(2);
  }
  if (a.suppress_window != 16 && a.suppress_rate == 0.0) {
    std::fprintf(stderr,
                 "--suppress-window requires --suppress-rate > 0 "
                 "(SPEC A.4) — it would be silently ignored\n");
    std::exit(2);
  }
  if (a.miss_rate > 0 && a.protocol != "dpos") {
    std::fprintf(stderr,
                 "--miss-rate (SPEC A.1) is a per-producer DPoS slot-fault "
                 "adversary; %s has no producer schedule and would silently "
                 "ignore it\n", a.protocol.c_str());
    std::exit(2);
  }
  if (a.max_delay_rounds > 16) {
    std::fprintf(stderr,
                 "--max-delay-rounds must be in [0, 16] (SPEC A.2)\n");
    std::exit(2);
  }
  if (a.desync_rate > 0 && a.protocol != "pbft" && a.protocol != "hotstuff") {
    std::fprintf(stderr,
                 "--desync-rate (SPEC B) skews the per-node view timers of "
                 "the pbft/hotstuff synchronizers; %s has no view timer and "
                 "would silently ignore it\n", a.protocol.c_str());
    std::exit(2);
  }
  if (a.max_skew_rounds < 1 || a.max_skew_rounds > 8) {
    std::fprintf(stderr, "--max-skew-rounds must be in [1, 8] (SPEC B)\n");
    std::exit(2);
  }
  if (a.max_skew_rounds != 1 && a.desync_rate == 0.0) {
    std::fprintf(stderr,
                 "--max-skew-rounds requires --desync-rate > 0 (SPEC B) — "
                 "it would be silently ignored\n");
    std::exit(2);
  }
  if (a.oracle_delivery != "auto" &&
      (a.protocol == "dpos" || a.protocol == "hotstuff")) {
    std::fprintf(stderr,
                 "--oracle-delivery: %s has no [N,N] delivery layer (one "
                 "producer/leader row per round is already edge-wise); the "
                 "flag would be silently ignored\n", a.protocol.c_str());
    std::exit(2);
  }
  return a;
}

// Canonical serialization (docs/SPEC.md §4; mirrors core/serialize.py).
struct Payload {
  std::vector<uint8_t> bytes;

  void u8(uint8_t v) { bytes.push_back(v); }
  void u32(uint32_t v) {
    for (int i = 0; i < 4; ++i) bytes.push_back(uint8_t(v >> (8 * i)));
  }
  void header(uint8_t proto_id, uint32_t B, uint32_t N) {
    bytes.insert(bytes.end(), {'C', 'T', 'P', 'U'});
    u8(1);  // version
    u8(proto_id);
    u32(B);
    u32(N);
  }
  void records(uint32_t count, const uint32_t* a, const uint32_t* b) {
    u32(count);
    for (uint32_t k = 0; k < count; ++k) {
      u32(a[k]);
      u32(b[k]);
    }
  }
};

double now_s() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return double(ts.tv_sec) + 1e-9 * double(ts.tv_nsec);
}

int run_cpu(const Args& a) {
  // Protocol-agnostic: everything below goes through the Engine seam
  // (engine.h) — configure by name, run, read uniform decided records.
  const uint32_t N = a.nodes, R = a.rounds, B = a.sweeps;
  const int proto_id = ctpu::protocol_id(a.protocol);
  if (proto_id < 0) {
    std::fprintf(stderr, "unknown protocol %s\n", a.protocol.c_str());
    return 2;
  }

  ctpu::SimConfig cfg;
  cfg.n_nodes = N;
  cfg.n_rounds = R;
  cfg.log_capacity = a.log_capacity;
  cfg.max_entries = a.max_entries;
  cfg.t_min = a.t_min;
  cfg.t_max = a.t_max;
  cfg.max_active = a.max_active;
  cfg.drop_cut = prob_threshold_u32(a.drop_rate);
  cfg.part_cut = prob_threshold_u32(a.partition_rate);
  cfg.churn_cut = prob_threshold_u32(a.churn_rate);
  cfg.crash_cut = prob_threshold_u32(a.crash_prob);
  cfg.recover_cut = prob_threshold_u32(a.recover_prob);
  cfg.max_crashed = a.max_crashed;
  cfg.miss_cut = prob_threshold_u32(a.miss_rate);
  cfg.max_delay = a.max_delay_rounds;
  cfg.suppress_cut = prob_threshold_u32(a.suppress_rate);
  cfg.suppress_window = a.suppress_window;
  cfg.net_switch = a.net_model == "switch" ? 1 : 0;
  cfg.n_aggregators = a.n_aggregators;
  cfg.agg_fail_cut = prob_threshold_u32(a.agg_fail_rate);
  cfg.agg_stale_cut = prob_threshold_u32(a.agg_stale_rate);
  cfg.agg_max_stale = a.agg_max_stale;
  cfg.agg_byz = a.agg_byz;
  cfg.agg_poison_cut = prob_threshold_u32(a.agg_poison_rate);
  cfg.byz_uplink_cut = prob_threshold_u32(a.byz_uplink_rate);
  cfg.desync_cut = prob_threshold_u32(a.desync_rate);
  cfg.max_skew = a.max_skew_rounds;
  cfg.f = a.f;
  cfg.view_timeout = a.view_timeout;
  cfg.n_byzantine = a.n_byzantine;
  cfg.byz_equivocate = a.byz_mode == "equivocate" ? 1 : 0;
  cfg.fault_bcast = a.fault_model == "bcast" ? 1 : 0;
  cfg.n_proposers = a.n_proposers;
  cfg.n_candidates = a.n_candidates;
  cfg.n_producers = a.n_producers;
  cfg.epoch_len = a.epoch_len;
  cfg.oracle_delivery = a.oracle_delivery == "dense" ? 1
                        : a.oracle_delivery == "edge" ? 2 : 0;

  Payload pl;
  pl.header(uint8_t(proto_id), B, N);

  // Records per node are bounded by the slot/log capacity for every
  // protocol, so one scratch pair serves the whole run.
  std::vector<uint32_t> rec_a(a.log_capacity), rec_b(a.log_capacity);

  double t0 = now_s();
  for (uint32_t b = 0; b < B; ++b) {
    std::unique_ptr<ctpu::Engine> eng = ctpu::make_engine(a.protocol);
    cfg.seed = a.seed + b;
    if (eng->run(cfg)) {
      std::fprintf(stderr, "%s: invalid config\n", eng->name());
      return 1;
    }
    for (uint32_t n = 0; n < N; ++n) {
      const uint32_t count = eng->decided_count(n);
      eng->decided_records(n, rec_a.data(), rec_b.data());
      pl.records(count, rec_a.data(), rec_b.data());
    }
  }
  double wall = now_s() - t0;

  if (!a.out_path.empty()) {
    FILE* fp = std::fopen(a.out_path.c_str(), "wb");
    if (!fp) { std::perror("fopen --out"); return 1; }
    std::fwrite(pl.bytes.data(), 1, pl.bytes.size(), fp);
    std::fclose(fp);
  }

  std::string digest = ctpu::sha256_hex(pl.bytes.data(), pl.bytes.size());
  uint64_t steps = uint64_t(B) * N * R;
  std::printf(
      "{\"protocol\": \"%s\", \"engine\": \"cpu\", \"platform\": \"oracle\", "
      "\"n_nodes\": %u, "
      "\"n_rounds\": %u, \"n_sweeps\": %u, \"seed\": %" PRIu64 ", "
      "\"steps\": %" PRIu64 ", \"wall_s\": %.6f, \"steps_per_sec\": %.1f, "
      "\"payload_bytes\": %zu, \"digest\": \"%s\"}\n",
      a.protocol.c_str(), N, R, B, a.seed, steps, wall,
      wall > 0 ? double(steps) / wall : 0.0, pl.bytes.size(), digest.c_str());
  return 0;
}

}  // namespace

namespace {

// The consensus_tpu package lives one directory above this binary
// (repo/cpp/consensus-sim → repo/). Prepend that to PYTHONPATH so the
// `--engine tpu` re-exec resolves from any working directory.
void export_repo_root_pythonpath() {
  char resolved[PATH_MAX];
  if (!realpath("/proc/self/exe", resolved)) return;
  std::string p(resolved);
  for (int up = 0; up < 2; ++up) {
    size_t slash = p.rfind('/');
    if (slash == std::string::npos) return;
    p.resize(slash);
  }
  const char* old = std::getenv("PYTHONPATH");
  std::string val = (old && *old) ? p + ":" + old : p;
  setenv("PYTHONPATH", val.c_str(), 1);
}

}  // namespace

int main(int argc, char** argv) {
  // One front door, two engines: if the user asked for the TPU engine,
  // hand the identical flag vector to the Python/JAX engine (the
  // pyo3-bridge analog, BASELINE.json:5) BEFORE strict flag parsing —
  // TPU-only flags (--mesh, --checkpoint, --profile, --config,
  // --scan-chunk) are the Python side's to validate, not ours.
  for (int i = 1; i + 1 < argc; ++i) {
    if (std::strcmp(argv[i], "--engine") == 0 &&
        std::strcmp(argv[i + 1], "tpu") == 0) {
      export_repo_root_pythonpath();
      std::vector<char*> args;
      args.push_back(const_cast<char*>("python3"));
      args.push_back(const_cast<char*>("-m"));
      args.push_back(const_cast<char*>("consensus_tpu"));
      for (int j = 1; j < argc; ++j) args.push_back(argv[j]);
      args.push_back(nullptr);
      execvp("python3", args.data());
      std::perror("execvp python3");
      return 127;
    }
  }
  Args a = parse(argc, argv);
  if (a.engine != "cpu") {
    std::fprintf(stderr, "unknown engine %s\n", a.engine.c_str());
    return 2;
  }
  return run_cpu(a);
}
