// consensus-sim — the native CLI driver (SURVEY.md §2 component 13).
//
// Plays the role of the reference's CLI binary: flags → Config → run →
// JSON report. The CPU engine is the in-process C++ oracle (oracle.cpp);
// `--engine tpu` re-execs `python3 -m consensus_tpu` with the same flags
// so one front door drives both engines, mirroring the reference's
// engine-pluggable `Consensus` trait seam (BASELINE.json:5).
//
// The JSON report contains the SHA-256 digest of the canonical decided-log
// serialization (docs/SPEC.md §4) — byte-identical to the Python side's
// `RunResult.digest`, so cross-engine equivalence is a string compare:
//
//   ./consensus-sim --protocol raft --nodes 5 --rounds 64 | jq .digest
//   ./consensus-sim --engine tpu  --protocol raft ...     | jq .digest

#include <cinttypes>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <ctime>
#include <string>
#include <vector>

#include <unistd.h>

#include "sha256.h"

extern "C" {
int ctpu_raft_run(uint64_t seed, uint32_t n_nodes, uint32_t n_rounds,
                  uint32_t log_capacity, uint32_t max_entries, uint32_t t_min,
                  uint32_t t_max, uint32_t drop_cut, uint32_t part_cut,
                  uint32_t churn_cut, uint32_t* out_commit,
                  uint32_t* out_log_term, uint32_t* out_log_val,
                  uint32_t* out_term, uint32_t* out_role);
int ctpu_pbft_run(uint64_t seed, uint32_t n_nodes, uint32_t n_rounds,
                  uint32_t n_slots, uint32_t f, uint32_t view_timeout,
                  uint32_t n_byzantine, uint32_t drop_cut, uint32_t part_cut,
                  uint32_t churn_cut, uint8_t* out_committed,
                  uint32_t* out_dval, uint32_t* out_view);
int ctpu_paxos_run(uint64_t seed, uint32_t n_nodes, uint32_t n_rounds,
                   uint32_t n_slots, uint32_t n_proposers, uint32_t drop_cut,
                   uint32_t part_cut, uint32_t churn_cut,
                   uint32_t* out_learned_val, uint8_t* out_learned_mask,
                   uint32_t* out_promised, uint32_t* out_acc_bal,
                   uint32_t* out_acc_val);
int ctpu_dpos_run(uint64_t seed, uint32_t n_nodes, uint32_t n_rounds,
                  uint32_t log_capacity, uint32_t n_candidates,
                  uint32_t n_producers, uint32_t epoch_len, uint32_t drop_cut,
                  uint32_t part_cut, uint32_t churn_cut, uint32_t* out_chain_r,
                  uint32_t* out_chain_p, uint32_t* out_chain_len);
}

namespace {

struct Args {
  std::string protocol = "raft";
  std::string engine = "cpu";
  uint32_t nodes = 5, rounds = 64, sweeps = 1;
  uint64_t seed = 0;
  uint32_t log_capacity = 128, max_entries = 100;
  uint32_t t_min = 3, t_max = 8;
  double drop_rate = 0.0, partition_rate = 0.0, churn_rate = 0.0;
  uint32_t f = 1, view_timeout = 8, n_byzantine = 0;
  uint32_t n_proposers = 0;
  uint32_t n_candidates = 16, n_producers = 4, epoch_len = 16;
  std::string out_path;  // optional: dump raw payload bytes
  bool nodes_given = false;
};

// Must equal consensus_tpu.core.rng.prob_threshold_u32 — both engines
// compare raw u32 draws against the same integer cutoffs.
uint32_t prob_threshold_u32(double p) {
  if (p <= 0.0) return 0;
  if (p >= 1.0) return 0xFFFFFFFFu;
  double v = p * 4294967296.0;
  uint64_t c = uint64_t(v);
  return c > 0xFFFFFFFFull ? 0xFFFFFFFFu : uint32_t(c);
}

[[noreturn]] void usage(const char* argv0, int code) {
  std::fprintf(
      stderr,
      "usage: %s [--protocol raft|pbft|paxos|dpos] [--engine cpu|tpu]\n"
      "  [--nodes N] [--rounds R] [--sweeps B] [--seed S]\n"
      "  [--log-capacity L] [--max-entries E] [--t-min T] [--t-max T]\n"
      "  [--drop-rate P] [--partition-rate P] [--churn-rate P]\n"
      "  [--f F] [--view-timeout T] [--n-byzantine K] [--n-proposers P]\n"
      "  [--candidates C] [--producers K] [--epoch-len E] [--out FILE]\n",
      argv0);
  std::exit(code);
}

Args parse(int argc, char** argv) {
  Args a;
  for (int i = 1; i < argc; ++i) {
    std::string k = argv[i];
    auto need = [&](const char* name) -> const char* {
      if (i + 1 >= argc) {
        std::fprintf(stderr, "missing value for %s\n", name);
        std::exit(2);
      }
      return argv[++i];
    };
    if (k == "--protocol") a.protocol = need(k.c_str());
    else if (k == "--engine") a.engine = need(k.c_str());
    else if (k == "--nodes") { a.nodes = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10)); a.nodes_given = true; }
    else if (k == "--rounds") a.rounds = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--sweeps") a.sweeps = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--seed") a.seed = std::strtoull(need(k.c_str()), nullptr, 10);
    else if (k == "--log-capacity") a.log_capacity = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--max-entries") a.max_entries = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--t-min") a.t_min = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--t-max") a.t_max = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--drop-rate") a.drop_rate = std::strtod(need(k.c_str()), nullptr);
    else if (k == "--partition-rate") a.partition_rate = std::strtod(need(k.c_str()), nullptr);
    else if (k == "--churn-rate") a.churn_rate = std::strtod(need(k.c_str()), nullptr);
    else if (k == "--f") a.f = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--view-timeout") a.view_timeout = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--n-byzantine") a.n_byzantine = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--n-proposers") a.n_proposers = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--candidates") a.n_candidates = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--producers") a.n_producers = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--epoch-len") a.epoch_len = uint32_t(std::strtoul(need(k.c_str()), nullptr, 10));
    else if (k == "--out") a.out_path = need(k.c_str());
    else if (k == "--help" || k == "-h") usage(argv[0], 0);
    else { std::fprintf(stderr, "unknown flag %s\n", k.c_str()); usage(argv[0], 2); }
  }
  if (a.protocol == "pbft" && !a.nodes_given) a.nodes = 3 * a.f + 1;
  return a;
}

// Canonical serialization (docs/SPEC.md §4; mirrors core/serialize.py).
struct Payload {
  std::vector<uint8_t> bytes;

  void u8(uint8_t v) { bytes.push_back(v); }
  void u32(uint32_t v) {
    for (int i = 0; i < 4; ++i) bytes.push_back(uint8_t(v >> (8 * i)));
  }
  void header(uint8_t proto_id, uint32_t B, uint32_t N) {
    bytes.insert(bytes.end(), {'C', 'T', 'P', 'U'});
    u8(1);  // version
    u8(proto_id);
    u32(B);
    u32(N);
  }
  void records(uint32_t count, const uint32_t* a, const uint32_t* b) {
    u32(count);
    for (uint32_t k = 0; k < count; ++k) {
      u32(a[k]);
      u32(b[k]);
    }
  }
  void sparse_records(uint32_t S, const uint8_t* mask, const uint32_t* val) {
    uint32_t count = 0;
    for (uint32_t s = 0; s < S; ++s) count += mask[s] ? 1 : 0;
    u32(count);
    for (uint32_t s = 0; s < S; ++s)
      if (mask[s]) {
        u32(s);
        u32(val[s]);
      }
  }
};

double now_s() {
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return double(ts.tv_sec) + 1e-9 * double(ts.tv_nsec);
}

int run_cpu(const Args& a) {
  const uint32_t N = a.nodes, R = a.rounds, B = a.sweeps;
  const uint32_t L = a.log_capacity;
  const uint32_t drop = prob_threshold_u32(a.drop_rate);
  const uint32_t part = prob_threshold_u32(a.partition_rate);
  const uint32_t churn = prob_threshold_u32(a.churn_rate);

  Payload pl;
  uint8_t proto_id = a.protocol == "raft"    ? 0
                     : a.protocol == "pbft"  ? 1
                     : a.protocol == "paxos" ? 2
                     : a.protocol == "dpos"  ? 3
                                             : 255;
  if (proto_id == 255) {
    std::fprintf(stderr, "unknown protocol %s\n", a.protocol.c_str());
    return 2;
  }
  pl.header(proto_id, B, N);

  double t0 = now_s();
  for (uint32_t b = 0; b < B; ++b) {
    uint64_t seed = a.seed + b;
    if (a.protocol == "raft") {
      std::vector<uint32_t> commit(N), term(N), role(N);
      std::vector<uint32_t> log_term(size_t(N) * L), log_val(size_t(N) * L);
      if (ctpu_raft_run(seed, N, R, L, a.max_entries, a.t_min, a.t_max, drop,
                        part, churn, commit.data(), log_term.data(),
                        log_val.data(), term.data(), role.data()))
        return 1;
      for (uint32_t n = 0; n < N; ++n)
        pl.records(commit[n], &log_term[size_t(n) * L], &log_val[size_t(n) * L]);
    } else if (a.protocol == "pbft") {
      std::vector<uint8_t> committed(size_t(N) * L);
      std::vector<uint32_t> dval(size_t(N) * L), view(N);
      if (ctpu_pbft_run(seed, N, R, L, a.f, a.view_timeout, a.n_byzantine,
                        drop, part, churn, committed.data(), dval.data(),
                        view.data()))
        return 1;
      for (uint32_t n = 0; n < N; ++n)
        pl.sparse_records(L, &committed[size_t(n) * L], &dval[size_t(n) * L]);
    } else if (a.protocol == "paxos") {
      std::vector<uint32_t> lval(size_t(N) * L), promised(size_t(N) * L),
          acc_bal(size_t(N) * L), acc_val(size_t(N) * L);
      std::vector<uint8_t> lmask(size_t(N) * L);
      if (ctpu_paxos_run(seed, N, R, L, a.n_proposers, drop, part, churn,
                         lval.data(), lmask.data(), promised.data(),
                         acc_bal.data(), acc_val.data()))
        return 1;
      for (uint32_t n = 0; n < N; ++n)
        pl.sparse_records(L, &lmask[size_t(n) * L], &lval[size_t(n) * L]);
    } else {  // dpos
      std::vector<uint32_t> chain_r(size_t(N) * L), chain_p(size_t(N) * L),
          chain_len(N);
      if (ctpu_dpos_run(seed, N, R, L, a.n_candidates, a.n_producers,
                        a.epoch_len, drop, part, churn, chain_r.data(),
                        chain_p.data(), chain_len.data()))
        return 1;
      for (uint32_t n = 0; n < N; ++n)
        pl.records(chain_len[n], &chain_r[size_t(n) * L], &chain_p[size_t(n) * L]);
    }
  }
  double wall = now_s() - t0;

  if (!a.out_path.empty()) {
    FILE* fp = std::fopen(a.out_path.c_str(), "wb");
    if (!fp) { std::perror("fopen --out"); return 1; }
    std::fwrite(pl.bytes.data(), 1, pl.bytes.size(), fp);
    std::fclose(fp);
  }

  std::string digest = ctpu::sha256_hex(pl.bytes.data(), pl.bytes.size());
  uint64_t steps = uint64_t(B) * N * R;
  std::printf(
      "{\"protocol\": \"%s\", \"engine\": \"cpu\", \"n_nodes\": %u, "
      "\"n_rounds\": %u, \"n_sweeps\": %u, \"seed\": %" PRIu64 ", "
      "\"steps\": %" PRIu64 ", \"wall_s\": %.6f, \"steps_per_sec\": %.1f, "
      "\"payload_bytes\": %zu, \"digest\": \"%s\"}\n",
      a.protocol.c_str(), N, R, B, a.seed, steps, wall,
      wall > 0 ? double(steps) / wall : 0.0, pl.bytes.size(), digest.c_str());
  return 0;
}

}  // namespace

int main(int argc, char** argv) {
  // One front door, two engines: if the user asked for the TPU engine,
  // hand the identical flag vector to the Python/JAX engine (the
  // pyo3-bridge analog, BASELINE.json:5) BEFORE strict flag parsing —
  // TPU-only flags (--mesh, --checkpoint, --profile, --config,
  // --scan-chunk) are the Python side's to validate, not ours.
  for (int i = 1; i + 1 < argc; ++i) {
    if (std::strcmp(argv[i], "--engine") == 0 &&
        std::strcmp(argv[i + 1], "tpu") == 0) {
      std::vector<char*> args;
      args.push_back(const_cast<char*>("python3"));
      args.push_back(const_cast<char*>("-m"));
      args.push_back(const_cast<char*>("consensus_tpu"));
      for (int j = 1; j < argc; ++j) args.push_back(argv[j]);
      args.push_back(nullptr);
      execvp("python3", args.data());
      std::perror("execvp python3");
      return 127;
    }
  }
  Args a = parse(argc, argv);
  if (a.engine != "cpu") {
    std::fprintf(stderr, "unknown engine %s\n", a.engine.c_str());
    return 2;
  }
  return run_cpu(a);
}
