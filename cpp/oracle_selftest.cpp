// Sanitizer selftest — runs every oracle protocol on small adversarial
// configs. Built with -fsanitize=address,undefined (`make san-test`), it
// is the framework's memory/UB check for the native engine (SURVEY.md §5
// "race detection / sanitizers": the Rust reference gets memory safety
// from the compiler; the C++ oracle earns it here). Exit 0 = clean.
//
// Also doubles as a determinism probe: each config runs twice and the
// outputs must match byte-for-byte (the oracle is a pure function of
// (config, seed); divergence would indicate uninitialized reads).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <vector>

extern "C" {
int ctpu_raft_run(uint64_t, uint32_t, uint32_t, uint32_t, uint32_t, uint32_t,
                  uint32_t, uint32_t, uint32_t, uint32_t, uint32_t, uint32_t,
                  uint32_t, uint32_t, uint32_t, uint32_t, uint32_t, uint32_t,
                  uint32_t, uint32_t, uint32_t, uint32_t, uint32_t,
                  uint32_t*, uint32_t*, uint32_t*, uint32_t*, uint32_t*);
int ctpu_pbft_run(uint64_t, uint32_t, uint32_t, uint32_t, uint32_t, uint32_t,
                  uint32_t, uint32_t, uint32_t, uint32_t, uint32_t, uint32_t,
                  uint32_t, uint32_t, uint32_t, uint32_t, uint32_t, uint32_t,
                  uint32_t, uint32_t, uint32_t, uint32_t, uint32_t, uint32_t,
                  uint32_t, uint32_t, uint32_t,
                  uint8_t*, uint32_t*, uint32_t*);
int ctpu_paxos_run(uint64_t, uint32_t, uint32_t, uint32_t, uint32_t, uint32_t,
                   uint32_t, uint32_t, uint32_t, uint32_t, uint32_t, uint32_t,
                   uint32_t, uint32_t, uint32_t, uint32_t, uint32_t, uint32_t,
                   uint32_t*, uint8_t*,
                   uint32_t*, uint32_t*, uint32_t*);
int ctpu_dpos_run(uint64_t, uint32_t, uint32_t, uint32_t, uint32_t, uint32_t,
                  uint32_t, uint32_t, uint32_t, uint32_t, uint32_t, uint32_t,
                  uint32_t, uint32_t, uint32_t, uint32_t, uint32_t,
                  uint32_t*, uint32_t*, uint32_t*, int32_t*);
int ctpu_hotstuff_run(uint64_t, uint32_t, uint32_t, uint32_t, uint32_t,
                      uint32_t, uint32_t, uint32_t, uint32_t, uint32_t,
                      uint32_t, uint32_t, uint32_t, uint32_t, uint32_t,
                      uint32_t, uint32_t, uint32_t, uint32_t, uint32_t,
                      uint32_t, uint32_t, uint32_t, uint32_t,
                      uint32_t,
                      uint8_t*, uint32_t*, uint32_t*, uint32_t*);
}

namespace {

// ~10% drop, ~5% partition, ~5% churn as u32 cutoffs (cf. prob_threshold).
constexpr uint32_t DROP = 429496729u;
constexpr uint32_t PART = 214748364u;
constexpr uint32_t CHURN = 214748364u;
// SPEC §6c / §A.1 cutoffs (~15% crash, ~30% recover, ~40% slot miss).
constexpr uint32_t CRASH = 644245094u;
constexpr uint32_t REC = 1288490188u;
constexpr uint32_t MISS = 1717986918u;

int fail(const char* what) {
  std::fprintf(stderr, "selftest FAILED: %s\n", what);
  return 1;
}

template <typename F>
int run_twice(const char* name, size_t out_words, F&& f) {
  std::vector<uint32_t> a(out_words, 0xDEADBEEFu), b(out_words, 0x12345678u);
  if (f(a.data()) != 0) return fail(name);
  if (f(b.data()) != 0) return fail(name);
  if (std::memcmp(a.data(), b.data(), out_words * 4) != 0) {
    std::fprintf(stderr, "selftest: %s nondeterministic\n", name);
    return 1;
  }
  std::printf("selftest: %-6s ok (%zu output words)\n", name, out_words);
  return 0;
}

// The delivery-strategy contract (oracle.cpp Net): DENSE (1) and EDGE
// (2) evaluate the same pure draw function, so outputs must match
// byte-for-byte. ``f`` takes (out, oracle_delivery).
template <typename F>
int run_match(const char* name, size_t out_words, F&& f) {
  std::vector<uint32_t> a(out_words, 0xDEADBEEFu), b(out_words, 0x12345678u);
  if (f(a.data(), 1u) != 0) return fail(name);
  if (f(b.data(), 2u) != 0) return fail(name);
  if (std::memcmp(a.data(), b.data(), out_words * 4) != 0) {
    std::fprintf(stderr, "selftest: %s dense/edge delivery diverge\n", name);
    return 1;
  }
  std::printf("selftest: %-6s ok (dense == edge, %zu words)\n", name,
              out_words);
  return 0;
}

}  // namespace

int main() {
  int rc = 0;
  {
    const uint32_t N = 7, R = 96, L = 64, E = 40;
    size_t W = N + 2 * size_t(N) * L + N + N;
    rc |= run_twice("raft", W, [&](uint32_t* o) {
      return ctpu_raft_run(99, N, R, L, E, 3, 8, DROP, PART, CHURN, 0, 0, 0,
                           0, 0, 0, 0, 0, /*§9 flat*/ 0, 0, 0, 0, 1,
                           o, o + N, o + N + size_t(N) * L,
                           o + N + 2 * size_t(N) * L,
                           o + 2 * N + 2 * size_t(N) * L);
    });
    // Capped engine (SPEC §3b): same shapes, max_active = 3.
    rc |= run_twice("raft-capped", W, [&](uint32_t* o) {
      return ctpu_raft_run(99, N, R, L, E, 3, 8, DROP, PART, CHURN, 3, 0, 0,
                           0, 0, 0, 0, 0, /*§9 flat*/ 0, 0, 0, 0, 1,
                           o, o + N, o + N + size_t(N) * L,
                           o + N + 2 * size_t(N) * L,
                           o + 2 * N + 2 * size_t(N) * L);
    });
    // SPEC §3c adversaries: withholding and double-granting minorities.
    rc |= run_twice("raft-byz-silent", W, [&](uint32_t* o) {
      return ctpu_raft_run(99, N, R, L, E, 3, 8, DROP, PART, CHURN, 0, 2, 0,
                           0, 0, 0, 0, 0, /*§9 flat*/ 0, 0, 0, 0, 1,
                           o, o + N, o + N + size_t(N) * L,
                           o + N + 2 * size_t(N) * L,
                           o + 2 * N + 2 * size_t(N) * L);
    });
    rc |= run_twice("raft-byz-equiv", W, [&](uint32_t* o) {
      return ctpu_raft_run(99, N, R, L, E, 3, 8, DROP, PART, CHURN, 0, 2, 1,
                           0, 0, 0, 0, 0, /*§9 flat*/ 0, 0, 0, 0, 1,
                           o, o + N, o + N + size_t(N) * L,
                           o + N + 2 * size_t(N) * L,
                           o + 2 * N + 2 * size_t(N) * L);
    });
    // Edge-wise vs dense delivery: byte-identical on both engines.
    rc |= run_match("raft-delivery", W, [&](uint32_t* o, uint32_t d) {
      return ctpu_raft_run(99, N, R, L, E, 3, 8, DROP, PART, CHURN, 0, 0, 0,
                           d, 0, 0, 0, 0, /*§9 flat*/ 0, 0, 0, 0, 1,
                           o, o + N, o + N + size_t(N) * L,
                           o + N + 2 * size_t(N) * L,
                           o + 2 * N + 2 * size_t(N) * L);
    });
    rc |= run_match("raft-capped-delivery", W, [&](uint32_t* o, uint32_t d) {
      return ctpu_raft_run(99, N, R, L, E, 3, 8, DROP, PART, CHURN, 3, 0, 0,
                           d, 0, 0, 0, 0, /*§9 flat*/ 0, 0, 0, 0, 1,
                           o, o + N, o + N + size_t(N) * L,
                           o + N + 2 * size_t(N) * L,
                           o + 2 * N + 2 * size_t(N) * L);
    });
    // SPEC §6c crash-recover + §A.2 delayed retransmission (the
    // adversary-library mirror), dense vs edge delivery.
    rc |= run_match("raft-crash-delay", W, [&](uint32_t* o, uint32_t d) {
      return ctpu_raft_run(99, N, R, L, E, 3, 8, DROP, PART, CHURN, 0, 0, 0,
                           d, CRASH, REC, 2, 4, /*§9 flat*/ 0, 0, 0, 0, 1, o, o + N,
                           o + N + size_t(N) * L,
                           o + N + 2 * size_t(N) * L,
                           o + 2 * N + 2 * size_t(N) * L);
    });
    rc |= run_match("raft-capped-crash", W, [&](uint32_t* o, uint32_t d) {
      return ctpu_raft_run(99, N, R, L, E, 3, 8, DROP, PART, CHURN, 3, 0, 0,
                           d, CRASH, REC, 0, 3, /*§9 flat*/ 0, 0, 0, 0, 1, o, o + N,
                           o + N + size_t(N) * L,
                           o + N + 2 * size_t(N) * L,
                           o + 2 * N + 2 * size_t(N) * L);
    });
  }
  {
    const uint32_t f = 2, N = 3 * f + 1, R = 48, S = 16;
    size_t ns = size_t(N) * S;
    // committed (u8, round up to words) + dval + view
    size_t W = (ns + 3) / 4 + ns + N;
    rc |= run_twice("pbft", W, [&](uint32_t* o) {
      return ctpu_pbft_run(77, N, R, S, f, 8, 1, 0, 0, DROP, PART, CHURN, 0, 0, 0, 0, 0,
                           /*§9 flat*/ 0, 0, 0, 0, 1, /*§9b flat*/ 0, 0, 0, /*§B off*/ 0, 1,
                           reinterpret_cast<uint8_t*>(o), o + (ns + 3) / 4,
                           o + (ns + 3) / 4 + ns);
    });
    rc |= run_twice("pbft-equiv", W, [&](uint32_t* o) {
      return ctpu_pbft_run(77, N, R, S, f, 8, 2, 1, 0, DROP, PART, CHURN, 0, 0, 0, 0, 0,
                           /*§9 flat*/ 0, 0, 0, 0, 1, /*§9b flat*/ 0, 0, 0, /*§B off*/ 0, 1,
                           reinterpret_cast<uint8_t*>(o), o + (ns + 3) / 4,
                           o + (ns + 3) / 4 + ns);
    });
    // SPEC §6b broadcast-atomic fault model, with equivocation.
    rc |= run_twice("pbft-bcast", W, [&](uint32_t* o) {
      return ctpu_pbft_run(77, N, R, S, f, 8, 2, 1, 1, DROP, PART, CHURN, 0, 0, 0, 0, 0,
                           /*§9 flat*/ 0, 0, 0, 0, 1, /*§9b flat*/ 0, 0, 0, /*§B off*/ 0, 1,
                           reinterpret_cast<uint8_t*>(o), o + (ns + 3) / 4,
                           o + (ns + 3) / 4 + ns);
    });
    // §6 edge model: dense vs forced edge-wise delivery queries.
    rc |= run_match("pbft-delivery", W, [&](uint32_t* o, uint32_t d) {
      return ctpu_pbft_run(77, N, R, S, f, 8, 2, 1, 0, DROP, PART, CHURN, d, 0, 0, 0, 0,
                           /*§9 flat*/ 0, 0, 0, 0, 1, /*§9b flat*/ 0, 0, 0, /*§B off*/ 0, 1,
                           reinterpret_cast<uint8_t*>(o), o + (ns + 3) / 4,
                           o + (ns + 3) / 4 + ns);
    });
    // §6b: the per-(slot, side) aggregate round (auto/edge) vs the
    // direct per-receiver definition (forced dense).
    rc |= run_match("pbft-bcast-agg", W, [&](uint32_t* o, uint32_t d) {
      return ctpu_pbft_run(77, N, R, S, f, 8, 2, 1, 1, DROP, PART, CHURN, d, 0, 0, 0, 0,
                           /*§9 flat*/ 0, 0, 0, 0, 1, /*§9b flat*/ 0, 0, 0, /*§B off*/ 0, 1,
                           reinterpret_cast<uint8_t*>(o), o + (ns + 3) / 4,
                           o + (ns + 3) / 4 + ns);
    });
    // §6b aggregate vs direct under §6c crash + §A.2 delay.
    rc |= run_match("pbft-bcast-crash", W, [&](uint32_t* o, uint32_t d) {
      return ctpu_pbft_run(77, N, R, S, f, 8, 2, 1, 1, DROP, PART, CHURN, d,
                           CRASH, REC, 2, 3, /*§9 flat*/ 0, 0, 0, 0, 1,
                           /*§9b flat*/ 0, 0, 0, /*§B off*/ 0, 1,
                           reinterpret_cast<uint8_t*>(o), o + (ns + 3) / 4,
                           o + (ns + 3) / 4 + ns);
    });
  }
  {
    // SPEC §7b chained HotStuff: composed drop/partition/churn, a
    // silent byzantine minority, and §6c crash + §A.2 delay.
    const uint32_t f = 2, N = 3 * f + 1, R = 96, S = 64;
    size_t ns = size_t(N) * S;
    size_t W = (ns + 3) / 4 + ns + N + N;
    rc |= run_twice("hotstuff", W, [&](uint32_t* o) {
      return ctpu_hotstuff_run(33, N, R, S, f, 8, 1, 0, DROP, PART, CHURN,
                               0, 0, 0, 0, /*§9 flat*/ 0, 0, 0, 0, 1,
                               /*§9b flat*/ 0, 0, 0, /*§B off*/ 0, 1,
                               reinterpret_cast<uint8_t*>(o),
                               o + (ns + 3) / 4, o + (ns + 3) / 4 + ns,
                               o + (ns + 3) / 4 + ns + N);
    });
    // SPEC §7c per-receiver equivocation: byzantine proposers hand each
    // receiver a value-id stance; QC tallies go per-value.
    rc |= run_twice("hotstuff-equiv", W, [&](uint32_t* o) {
      return ctpu_hotstuff_run(33, N, R, S, f, 8, 2, 1, DROP, PART, CHURN,
                               0, 0, 0, 0, /*§9 flat*/ 0, 0, 0, 0, 1,
                               /*§9b flat*/ 0, 0, 0, /*§B off*/ 0, 1,
                               reinterpret_cast<uint8_t*>(o),
                               o + (ns + 3) / 4, o + (ns + 3) / 4 + ns,
                               o + (ns + 3) / 4 + ns + N);
    });
    rc |= run_twice("hotstuff-crash-delay", W, [&](uint32_t* o) {
      return ctpu_hotstuff_run(33, N, R, S, f, 8, 0, 0, DROP, PART, CHURN,
                               CRASH, REC, 2, 4, /*§9 flat*/ 0, 0, 0, 0, 1,
                               /*§9b flat*/ 0, 0, 0, /*§B off*/ 0, 1,
                               reinterpret_cast<uint8_t*>(o),
                               o + (ns + 3) / 4, o + (ns + 3) / 4 + ns,
                               o + (ns + 3) / 4 + ns + N);
    });
  }
  {
    const uint32_t N = 9, R = 32, S = 16;
    size_t ns = size_t(N) * S;
    size_t W = ns + (ns + 3) / 4 + 3 * ns;
    rc |= run_twice("paxos", W, [&](uint32_t* o) {
      return ctpu_paxos_run(55, N, R, S, 0, DROP, PART, CHURN, 0, 0, 0, 0, 0,
                            /*§9 flat*/ 0, 0, 0, 0, 1, o,
                            reinterpret_cast<uint8_t*>(o + ns), o + ns + (ns + 3) / 4,
                            o + ns + (ns + 3) / 4 + ns, o + ns + (ns + 3) / 4 + 2 * ns);
    });
    rc |= run_match("paxos-delivery", W, [&](uint32_t* o, uint32_t d) {
      return ctpu_paxos_run(55, N, R, S, 2, DROP, PART, CHURN, d, 0, 0, 0, 0,
                            /*§9 flat*/ 0, 0, 0, 0, 1, o,
                            reinterpret_cast<uint8_t*>(o + ns), o + ns + (ns + 3) / 4,
                            o + ns + (ns + 3) / 4 + ns, o + ns + (ns + 3) / 4 + 2 * ns);
    });
  }
  {
    const uint32_t V = 64, R = 64, L = 64, C = 16, K = 4, EP = 16;
    size_t vl = size_t(V) * L;
    size_t W = 2 * vl + 2 * V;  // chains + chain_len + lib
    rc |= run_twice("dpos", W, [&](uint32_t* o) {
      return ctpu_dpos_run(33, V, R, L, C, K, EP, DROP, PART, CHURN, 0, 0, 0,
                           0, 0, /*§A.4 off*/ 0, 16, o, o + vl,
                           o + 2 * vl,
                           reinterpret_cast<int32_t*>(o + 2 * vl + V));
    });
    // §A.1 slot miss + §A.2 delay + §6c crash composed.
    rc |= run_twice("dpos-adversary", W, [&](uint32_t* o) {
      return ctpu_dpos_run(33, V, R, L, C, K, EP, DROP, PART, CHURN,
                           CRASH, REC, 5, MISS, 4, /*§A.4*/ MISS, 24,
                           o, o + vl,
                           o + 2 * vl,
                           reinterpret_cast<int32_t*>(o + 2 * vl + V));
    });
  }
  {
    // SPEC §9 switch model: composed aggregator failure + stale state
    // with drop/partition/churn (+ §6c crash, §A.2 delay) for every
    // switch-capable protocol — determinism under sanitizers.
    const uint32_t AGGF = 644245094u, AGGS = 1288490188u;  // ~15%, ~30%
    {
      const uint32_t N = 9, R = 64, L = 32, E = 24;
      size_t W = N + 2 * size_t(N) * L + N + N;
      rc |= run_twice("raft-switch", W, [&](uint32_t* o) {
        return ctpu_raft_run(99, N, R, L, E, 3, 8, DROP, PART, CHURN, 0, 0,
                             0, 0, CRASH, REC, 2, 2,
                             /*§9 switch*/ 1, 3, AGGF, AGGS, 3,
                             o, o + N, o + N + size_t(N) * L,
                             o + N + 2 * size_t(N) * L,
                             o + 2 * N + 2 * size_t(N) * L);
      });
      rc |= run_twice("raft-capped-switch", W, [&](uint32_t* o) {
        return ctpu_raft_run(99, N, R, L, E, 3, 8, DROP, PART, CHURN, 3, 0,
                             0, 0, 0, 0, 0, 0,
                             /*§9 switch*/ 1, 3, AGGF, AGGS, 3,
                             o, o + N, o + N + size_t(N) * L,
                             o + N + 2 * size_t(N) * L,
                             o + 2 * N + 2 * size_t(N) * L);
      });
    }
    {
      const uint32_t f = 2, N = 3 * f + 1, R = 48, S = 16;
      size_t ns = size_t(N) * S;
      size_t W = (ns + 3) / 4 + ns + N;
      rc |= run_twice("pbft-switch", W, [&](uint32_t* o) {
        return ctpu_pbft_run(77, N, R, S, f, 8, 2, 1, 0, DROP, PART, CHURN,
                             0, CRASH, REC, 2, 2,
                             /*§9 switch*/ 1, 3, AGGF, AGGS, 3,
                             /*§9b off*/ 0, 0, 0, /*§B off*/ 0, 1,
                             reinterpret_cast<uint8_t*>(o), o + (ns + 3) / 4,
                             o + (ns + 3) / 4 + ns);
      });
      rc |= run_twice("pbft-bcast-switch", W, [&](uint32_t* o) {
        return ctpu_pbft_run(77, N, R, S, f, 8, 2, 1, 1, DROP, PART, CHURN,
                             0, 0, 0, 0, 2,
                             /*§9 switch*/ 1, 3, AGGF, AGGS, 3,
                             /*§9b off*/ 0, 0, 0, /*§B off*/ 0, 1,
                             reinterpret_cast<uint8_t*>(o), o + (ns + 3) / 4,
                             o + (ns + 3) / 4 + ns);
      });
    }
    {
      const uint32_t N = 9, R = 32, S = 16;
      size_t ns = size_t(N) * S;
      size_t W = ns + (ns + 3) / 4 + 3 * ns;
      rc |= run_twice("paxos-switch", W, [&](uint32_t* o) {
        return ctpu_paxos_run(55, N, R, S, 0, DROP, PART, CHURN, 0,
                              CRASH, REC, 2, 2,
                              /*§9 switch*/ 1, 3, AGGF, AGGS, 3, o,
                              reinterpret_cast<uint8_t*>(o + ns),
                              o + ns + (ns + 3) / 4,
                              o + ns + (ns + 3) / 4 + ns,
                              o + ns + (ns + 3) / 4 + 2 * ns);
      });
    }
    {
      const uint32_t f = 2, N = 3 * f + 1, R = 96, S = 64;
      size_t ns = size_t(N) * S;
      size_t W = (ns + 3) / 4 + ns + N + N;
      rc |= run_twice("hotstuff-switch", W, [&](uint32_t* o) {
        return ctpu_hotstuff_run(33, N, R, S, f, 4, 1, 0, DROP, PART, CHURN,
                                 CRASH, REC, 2, 2,
                                 /*§9 switch*/ 1, 2, AGGF, AGGS, 4,
                                 /*§9b off*/ 0, 0, 0, /*§B off*/ 0, 1,
                                 reinterpret_cast<uint8_t*>(o),
                                 o + (ns + 3) / 4, o + (ns + 3) / 4 + ns,
                                 o + (ns + 3) / 4 + ns + N);
      });
    }
    // SPEC §9b poisoned aggregation: byzantine combine forgery on a
    // tail aggregator plus lying uplinks, composed with §7c
    // equivocation — the silent-fork axes under sanitizers.
    {
      const uint32_t AGGP = 1717986918u, UPL = 858993459u;  // ~40%, ~20%
      const uint32_t f = 2, N = 3 * f + 1;
      {
        const uint32_t R = 48, S = 16;
        size_t ns = size_t(N) * S;
        size_t W = (ns + 3) / 4 + ns + N;
        rc |= run_twice("pbft-poison", W, [&](uint32_t* o) {
          return ctpu_pbft_run(77, N, R, S, f, 8, 2, 1, 1, DROP, PART, CHURN,
                               0, CRASH, REC, 2, 2,
                               /*§9 switch*/ 1, 3, AGGF, AGGS, 3,
                               /*§9b*/ 1, AGGP, UPL, /*§B off*/ 0, 1,
                               reinterpret_cast<uint8_t*>(o), o + (ns + 3) / 4,
                               o + (ns + 3) / 4 + ns);
        });
      }
      {
        const uint32_t R = 96, S = 64;
        size_t ns = size_t(N) * S;
        size_t W = (ns + 3) / 4 + ns + N + N;
        rc |= run_twice("hotstuff-poison", W, [&](uint32_t* o) {
          return ctpu_hotstuff_run(33, N, R, S, f, 4, 2, 1, DROP, PART, CHURN,
                                   CRASH, REC, 2, 2,
                                   /*§9 switch*/ 1, 2, AGGF, AGGS, 4,
                                   /*§9b*/ 1, AGGP, UPL, /*§B off*/ 0, 1,
                                   reinterpret_cast<uint8_t*>(o),
                                   o + (ns + 3) / 4, o + (ns + 3) / 4 + ns,
                                   o + (ns + 3) / 4 + ns + N);
        });
      }
    }
  }
  if (rc == 0) std::printf("selftest: ALL CLEAN\n");
  return rc;
}
