// The native engine seam — the C++ analog of the reference's pluggable
// `Consensus` trait (SURVEY.md §2 component 1, BASELINE.json:5: a new
// backend slots in behind one interface and "the CLI and
// network::Simulator driver are unchanged").
//
// `consensus-sim` (the native CLI) is written against this interface
// only: it configures an Engine by name, runs it, and serializes the
// decided log through the uniform record accessors — it has no
// per-protocol knowledge. The Python side's equivalent seam is
// consensus_tpu.network.runner.EngineDef.
#pragma once

#include <cstdint>
#include <memory>
#include <string>

namespace ctpu {

// One config schema shared by every engine (mirrors
// consensus_tpu.core.config.Config; unused fields ignored per protocol).
struct SimConfig {
  uint64_t seed = 0;
  uint32_t n_nodes = 5;
  uint32_t n_rounds = 64;
  uint32_t log_capacity = 128;  // raft log length / pbft+paxos slots / dpos chain
  uint32_t max_entries = 100;
  uint32_t t_min = 3, t_max = 8;
  uint32_t max_active = 0;  // raft: 0 = dense, >0 = SPEC §3b active cap
  uint32_t drop_cut = 0, part_cut = 0, churn_cut = 0;  // u32 cutoffs
  uint32_t f = 1, view_timeout = 8, n_byzantine = 0;   // pbft
  uint32_t byz_equivocate = 0;  // pbft byz_mode == "equivocate" (SPEC §6)
  uint32_t fault_bcast = 0;     // pbft fault_model == "bcast" (SPEC §6b)
  uint32_t n_proposers = 0;                            // paxos
  uint32_t n_candidates = 16, n_producers = 4, epoch_len = 16;  // dpos
  // SPEC §6c crash-recover adversary (mirrored scalar-for-scalar since
  // the adversary-library PR): per round each up node crashes with
  // crash_cut (capped at max_crashed simultaneously down; 0 = no cap)
  // and each down node recovers with recover_cut, rejoining from its
  // persisted state.
  uint32_t crash_cut = 0, recover_cut = 0, max_crashed = 0;
  // SPEC §A.1 per-producer DPoS slot-fault cutoff (dpos only).
  uint32_t miss_cut = 0;
  // SPEC §A.2 bounded message delay: a dropped flight may arrive via a
  // retransmission d <= max_delay rounds later (threefry.h delayed_open).
  uint32_t max_delay = 0;
  // SPEC §9 in-network vote aggregation (net_model="switch"): the
  // vote/quorum responses of raft/pbft/paxos/hotstuff route through
  // n_aggregators aggregator vertices (contiguous node segments);
  // STREAM_AGG drives the per-(round, aggregator) failure (a down
  // aggregator silently drops its whole segment) and stale-serve
  // (uplink re-drawn against a shifted round key, depth <= max_stale)
  // fault axes. Not a dpos model (the producer row doesn't vote).
  uint32_t net_switch = 0, n_aggregators = 0;
  uint32_t agg_fail_cut = 0, agg_stale_cut = 0, agg_max_stale = 1;
  // SPEC §9b poisoned aggregation (pbft/hotstuff switch models only):
  // the last agg_byz aggregator vertices serve forged full-segment
  // tallies with probability agg_poison_cut per (round, aggregator),
  // and each byzantine node lies to its switch uplink with probability
  // byz_uplink_cut per round (STREAM_POISON subdraws 0/1/2).
  uint32_t agg_byz = 0, agg_poison_cut = 0, byz_uplink_cut = 0;
  // SPEC §A.4 correlated DPoS producer suppression: one draw per
  // (round / suppress_window, producer) — a suppressed producer misses
  // every slot inside the window (dpos only).
  uint32_t suppress_cut = 0, suppress_window = 16;
  // SPEC §B per-node view-synchronizer timer skew (pbft, hotstuff —
  // the per-node pacemakers): each up node's local view timer jumps
  // ahead by 1 + (depth draw % max_skew) rounds with probability
  // desync_cut per (round, node) (STREAM_DESYNC subdraws 0/1),
  // firing premature local timeouts that desynchronize views.
  uint32_t desync_cut = 0, max_skew = 1;
  // Oracle delivery-layer strategy (execution only — decided logs are
  // byte-identical either way, SPEC §2 draws are pure counter functions):
  // 0 = auto (per-engine choice), 1 = dense [N,N] materialization,
  // 2 = on-demand edge-wise queries (O(live edges) per round — what makes
  // the capped 100k-node configs oracle-tractable, docs/PERF.md).
  uint32_t oracle_delivery = 0;
};

// A consensus engine: run the whole simulation, then expose each node's
// decided log as (a, b) u32 record pairs in canonical order
// (docs/SPEC.md §4 / core/serialize.py).
class Engine {
 public:
  virtual ~Engine() = default;
  virtual const char* name() const = 0;
  // Returns 0 on success, nonzero on invalid config.
  virtual int run(const SimConfig& cfg) = 0;
  virtual uint32_t n_nodes() const = 0;
  virtual uint32_t decided_count(uint32_t node) const = 0;
  // Fill a[0..count) and b[0..count) for `node` (count = decided_count).
  virtual void decided_records(uint32_t node, uint32_t* a, uint32_t* b) const = 0;
};

// Factory over the protocol registry. Returns nullptr for unknown names.
std::unique_ptr<Engine> make_engine(const std::string& protocol);

// Canonical protocol ids for the serialized header (serialize.py).
int protocol_id(const std::string& protocol);

}  // namespace ctpu
