"""Profile the flagship raft-1024x1024 kernel on the chip (VERDICT r3 #1).

Two outputs:
  * a jax.profiler trace (Perfetto) under benchmarks/traces/<tag>/ for
    offline inspection (steady-state only — the compile is excluded);
  * an ablation table on stderr: wall-clock of the full round kernel vs
    variants with one component disabled, measured on the same shapes.
    The deltas localize time sinks without a trace viewer (no GUI here).

Current ablations (vs the CURRENT kernel):
  * "cheap delivery" — replaces the SPEC §2 delivery mixer with one draw
    broadcast to all edges: the remaining cost of delivery randomness.
  * "timers only"    — P0+P1+P4 only: the non-[N,N] floor.

The historical round-4 attribution quoted in docs/PERF.md (commit-sort
45.1%, delivery *threefry* 24.3%) was measured with this script against
the PRE-optimization kernel (jnp.sort commit advance + threefry
delivery); those two components no longer exist in the committed kernel,
so those numbers are not reproducible from HEAD — that is the point of
the optimization. The ablated kernels are *wrong* (they skip protocol
semantics) — they exist only to attribute time; nothing here feeds the
differential tests.

Usage: python benchmarks/profile_raft.py [--nodes 1024] [--rounds 256]
"""
from __future__ import annotations

import argparse
import sys
import time

from consensus_tpu.utils.platform import ensure_platform

ensure_platform("auto")

import jax
import jax.numpy as jnp

from consensus_tpu.core import rng
from consensus_tpu.core.config import Config
from consensus_tpu.engines import raft
from consensus_tpu.network import runner


def log(msg):
    print(f"profile: {msg}", file=sys.stderr, flush=True)


def timed_scan(cfg, round_fn, seeds, n_rounds, tag, repeats=3,
               trace_dir=None):
    """Scan `round_fn` (cfg-bound) over n_rounds, vmapped over sweeps.

    Cache-proof (ROADMAP "Tunnel-cache audit", ADVICE r5): the tunnel
    backend caches byte-identical dispatches, so re-dispatching the same
    seed vector can replay a cached result and overstate steps/sec —
    exactly what PR 1 fixed for the full-run timings
    (benchmarks/run_benchmarks.py time_tpu). Each timed repeat therefore
    runs a DIFFERENT seed vector, offset by (rep+1)*n_sweeps — the same
    lo32(seed + b) lattice the runner derives, shifted past every
    trajectory any other repeat dispatched. The kernels are branchless
    with seed-independent shapes, so per-seed work (and throughput) is
    identical across repeats; any digest/sanity read still comes from
    the base-seed warmup state (`seeds` as passed in), which is also
    what the optional profiler trace captures.
    """

    @jax.jit
    def prog(seeds):
        carry = jax.vmap(lambda s: raft.raft_init(cfg, s))(seeds)

        def body(c, r):
            return jax.vmap(lambda s: round_fn(cfg, s, r))(c), None

        carry, _ = jax.lax.scan(body, carry,
                                jnp.arange(n_rounds, dtype=jnp.int32))
        return carry

    import numpy as np

    def sync(o):
        # The axon tunnel's block_until_ready is a no-op (experimental
        # plugin); a host transfer is the only reliable barrier.
        return np.asarray(o.commit).sum()

    sync(prog(seeds))  # compile + warm; base-seed state
    if trace_dir is not None:
        # Trace only a steady-state execution — tracing the compile
        # drowns the device timeline in host-side jaxpr events. The
        # traced dispatch reuses the warm base-seed input: a cache
        # replay would show up as an empty device timeline, which is
        # self-diagnosing, and the trace should depict the same state
        # the digest describes.
        with jax.profiler.trace(str(trace_dir)):
            sync(prog(seeds))
    best = float("inf")
    for rep in range(repeats):
        # lo32 wrap-around matches the runner's seed lattice exactly.
        varied = seeds + jnp.uint32((rep + 1) * seeds.shape[0])
        t0 = time.perf_counter()
        sync(prog(varied))
        best = min(best, time.perf_counter() - t0)
    steps = seeds.shape[0] * cfg.n_nodes * n_rounds
    log(f"{tag:28s} {best:7.3f}s  {steps / best / 1e6:7.2f}M steps/s")
    return best


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--nodes", type=int, default=1024)
    ap.add_argument("--rounds", type=int, default=256)
    ap.add_argument("--sweeps", type=int, default=8)
    ap.add_argument("--trace", action="store_true",
                    help="also capture a jax.profiler trace of the full kernel")
    args = ap.parse_args()

    cfg = Config(protocol="raft", engine="tpu", n_nodes=args.nodes,
                 n_rounds=args.rounds, n_sweeps=args.sweeps,
                 log_capacity=128, max_entries=112,
                 drop_rate=0.01, churn_rate=0.001, seed=42)
    seeds = jnp.asarray(runner.make_seeds(cfg))
    log(f"device={jax.devices()[0]} N={args.nodes} R={args.rounds} "
        f"S={args.sweeps}")

    # --- full kernel ---------------------------------------------------
    t_full = timed_scan(cfg, raft.raft_round, seeds, args.rounds, "full")

    t_nodel = timed_scan(cfg, _cheap_delivery_round, seeds, args.rounds,
                         "cheap delivery (ablate mixer)")
    t_nomsg = timed_scan(cfg, _timers_only_round, seeds, args.rounds,
                         "timers only (no [N,N])")

    log("--- attribution (deltas vs full) ---")
    log(f"delivery mixer       : {t_full - t_nodel:7.3f}s "
        f"({100 * (t_full - t_nodel) / t_full:4.1f}%)")
    log(f"all [N,N] phases     : {t_full - t_nomsg:7.3f}s "
        f"({100 * (t_full - t_nomsg) / t_full:4.1f}%)")

    if args.trace:
        import pathlib
        trace_rounds = min(args.rounds, 64)
        tdir = pathlib.Path(__file__).parent / "traces" / \
            f"raft{args.nodes}x{trace_rounds}"
        tdir.mkdir(parents=True, exist_ok=True)
        timed_scan(cfg, raft.raft_round, seeds, trace_rounds,
                   "traced", repeats=1, trace_dir=tdir)
        log(f"trace written to {tdir}")


# --- ablated round variants (wrong on purpose; timing only) ---------------

def _cheap_delivery_round(cfg, st, r):
    """Full round but the [N,N] delivery mask uses ONE threefry draw
    broadcast to all edges — isolates the per-edge draw cost (the SPEC
    mixer at HEAD; threefry before round 4)."""
    from consensus_tpu.ops import adversary
    orig = adversary.delivery

    def cheap(seed, N, rr, drop_cut, part_cut):
        one = rng.random_u32_jnp(seed, rng.STREAM_DELIVER, rr, 0, 0)
        i = jnp.arange(N, dtype=jnp.uint32)[:, None]
        j = jnp.arange(N, dtype=jnp.uint32)[None, :]
        bit = ((one >> ((i * 7 + j) % jnp.uint32(32))) & 1).astype(bool)
        return bit & (i != j)

    try:
        adversary.delivery = cheap
        raft._delivery = cheap
        return raft.raft_round(cfg, st, r)
    finally:
        adversary.delivery = orig
        raft._delivery = orig


def _timers_only_round(cfg, st, r):
    """P0+P1+P4 only — no message exchange at all. Lower bound for the
    non-[N,N] part of the kernel."""
    N = cfg.n_nodes
    idx = jnp.arange(N, dtype=jnp.int32)
    uidx = idx.astype(jnp.uint32)
    ur = jnp.asarray(r, jnp.uint32)
    seed = st.seed
    churn = raft._draw(seed, rng.STREAM_CHURN, ur, 0, 0) < raft._lt(
        cfg.churn_cutoff)
    term, role, voted_for = st.term, st.role, st.voted_for
    timer, timeout = st.timer, st.timeout
    stepdown = churn & (role == raft.ROLE_L)
    role = jnp.where(stepdown, raft.ROLE_F, role)
    timer = jnp.where(stepdown, 0, timer)
    cand_new = (role != raft.ROLE_L) & (timer >= timeout)
    term = term + cand_new.astype(jnp.int32)
    role = jnp.where(cand_new, raft.ROLE_C, role)
    voted_for = jnp.where(cand_new, idx, voted_for)
    timer = jnp.where(cand_new | stepdown, 0, timer + 1)
    timeout = jnp.where(cand_new,
                        raft._draw_timeout(seed, cfg.t_min, cfg.t_max, term,
                                           uidx), timeout)
    return raft.RaftState(seed, term, role, voted_for, st.log_term,
                          st.log_val, st.log_len, st.commit, timer, timeout,
                          st.match_idx, st.next_idx, st.down)


if __name__ == "__main__":
    main()
