"""Benchmark suite over the five BASELINE.md configs.

Measures node-round-steps/sec for the TPU engine and (where tractable)
the single-core C++ oracle, producing the oracle baseline BASELINE.md
calls for ("First measurement milestone") plus the TPU speedup.

Writes benchmarks/RESULTS.json and prints a table. Run on the TPU chip:

    python benchmarks/run_benchmarks.py [--quick] [--skip-oracle]

Oracle tractability: since the edge-wise delivery layer (cpp/oracle.cpp
Net EDGE mode + the O(A·N) capped iteration, docs/PERF.md "oracle
asymptotics"), the oracle runs EVERY BASELINE config at its TRUE shape
— so each flagship row pairs the TPU digest with an oracle digest of
the same config (benchmarks/parts/oracle-100k.json). The last holdout,
dense raft-1kx1k, fell to arithmetic: the old "~10^13 mixer evals ≈ a
day single-core" estimate was ~100x off (the dense Net materializes the
[N, N] matrix ONCE per round — one mixer chain per pair per round,
8 x 1024 x 1024^2 ≈ 8.6e9 total), and the measured full-shape run is
~42 s with a digest byte-equal to the committed on-chip TPU row
(pinned by tests/test_oracle_benchscale.py).
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import pathlib
import sys
import time

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from consensus_tpu.core.config import Config  # noqa: E402

ADV = dict(drop_rate=0.01, churn_rate=0.001)

# The five BASELINE.json configs (B:6-12), sized for the TPU engine.
CONFIGS = {
    # 1. Raft 5-node leader election + 100-entry replication. Tiny per
    # instance — batched 512 sweeps wide to give the chip actual work.
    "raft-5node": Config(protocol="raft", n_nodes=5, n_rounds=160,
                         n_sweeps=512, log_capacity=128, max_entries=100,
                         seed=1, **ADV),
    # 2. Raft 1k-node x 1k-round batched log-match sweep.
    "raft-1kx1k": Config(protocol="raft", n_nodes=1024, n_rounds=1024,
                         n_sweeps=8, log_capacity=128, max_entries=100,
                         seed=2, **ADV),
    # 2b. The north-star scale (BASELINE.json:5 "100k-node Raft sweeps"):
    # the SPEC §3b capped engine — O(A*N) per round; the dense [N,N]
    # design cannot represent this population on any chip.
    "raft-100k": Config(protocol="raft", n_nodes=100_000, n_rounds=64,
                        n_sweeps=8, log_capacity=128, max_entries=100,
                        max_active=8, seed=6, **ADV),
    # 3. PBFT f-sweep: shapes differ per f (N = 3f+1), so each f compiles
    # its own program; report the aggregate. Full 1..128 sweep is hours of
    # compiles — benchmark the power-of-two ladder.
    # (handled specially below)
    # 3b. PBFT at the north-star population (BASELINE.json:5 "100k-node
    # Raft+PBFT sweeps"): the SPEC §6b broadcast-atomic fault model —
    # O(N·S·log N) tallies; the §6 dense [N,N,S] tensors cannot exist at
    # this N. N = 3f+1.
    # (The earlier gather-based tally faulted the TPU worker when >=2
    # sweeps batched into one program — cfg.sweep_chunk bounded it; the
    # gather-free sorted-space tally needs no grouping at any width.)
    "pbft-100k-bcast": Config(protocol="pbft", fault_model="bcast",
                              f=33_333, n_nodes=100_000, n_rounds=64,
                              n_sweeps=8, log_capacity=16, seed=7, **ADV),
    # 3c. The linear-communication BFT flagship (ROADMAP "HotStuff-class
    # past the PBFT ceiling"): same population/tolerance as
    # pbft-100k-bcast (N = 3f+1 = 100k), but every phase is a threshold
    # count at the round leader — O(N) star delivery, zero sorts, an
    # O(N + S) carry (SPEC §7b). log_capacity 64 so the chained
    # pipeline commits one block per round for the WHOLE run (the §6b
    # pbft shape saturates its 16 slots; hotstuff has no [N, S] carry
    # to bound, so the flagship measures steady-state pipelining).
    "hotstuff-100k": Config(protocol="hotstuff", f=33_333,
                            n_nodes=100_000, n_rounds=64, n_sweeps=8,
                            log_capacity=64, seed=8, **ADV),
    # 4. Multi-decree Paxos 10k acceptors x 10k slots.
    "paxos-10kx10k": Config(protocol="paxos", n_nodes=10_000, n_rounds=16,
                            n_sweeps=1, log_capacity=10_000, seed=4, **ADV),
    # 5. DPoS 100k validators x epoch schedule.
    "dpos-100k": Config(protocol="dpos", n_nodes=100_000, n_rounds=256,
                        n_sweeps=1, log_capacity=256, n_candidates=1024,
                        n_producers=21, epoch_len=32, seed=5, **ADV),
}

PBFT_FS = [1, 2, 4, 8, 16, 32, 64, 128]

# Oracle-sized stand-ins — fully RETIRED: every BASELINE config runs
# the oracle at its true flagship shape (measured wall times in
# benchmarks/parts/oracle-100k.json and docs/PERF.md). The capped/
# aggregate configs fell to the edge-wise delivery layer; the last
# holdout, dense raft-1kx1k, fell to arithmetic — the dense Net
# materializes one mixer chain per pair per round (~8.6e9 for the full
# 8x1024x1024-round shape ≈ 42 s single-core), not the ~10^13 the old
# stand-in comment estimated. Kept (empty) so older drivers' .get()
# lookups stay valid.
ORACLE_SIZED: dict[str, Config] = {}

# Flagship-shape oracle rows are minutes-class, not seconds-class —
# measure once instead of best-of-2 (single-core C++ has no warmup
# effect worth a second multi-minute run).
ORACLE_ONE_REPEAT = {"raft-100k", "pbft-100k-bcast", "paxos-10kx10k",
                     "dpos-100k", "raft-1kx1k", "hotstuff-100k"}

# Dispatch-bound configs: the whole 5-node run is sub-millisecond of
# device time, so back-to-back separate dispatches time the tunnel's
# jitter (±30% run-to-run in committed RESULTS) — time them as ONE
# dispatch scanning over repeat lanes instead (time_tpu_repeat_scan).
REPEAT_SCAN = {"raft-5node"}

# HBM bandwidth of the chip the committed rows ran on (TPU v5 lite /
# v5e: 819 GB/s per chip) — the denominator that turns steps/sec into a
# %-of-peak figure a perf claim can be judged against (docs/PERF.md
# §"Achieved bandwidth").
HBM_PEAK_GBPS = 819.0


def carry_nbytes(cfg: Config) -> int:
    """Byte size of the batched scan carry, from the engine's state
    schema via jax.eval_shape — no buffer is ever allocated, so this is
    safe to run for 100k-node configs on any host."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from consensus_tpu.network import simulator
    eng = simulator.engine_def(cfg)
    tpl = jax.eval_shape(
        lambda s: jax.vmap(lambda x: eng.make_carry(cfg, x))(s),
        jax.ShapeDtypeStruct((cfg.n_sweeps,), jnp.uint32))
    return sum(int(np.prod(leaf.shape)) * leaf.dtype.itemsize
               for leaf in jax.tree.leaves(tpl))


def bandwidth_stats(cfg: Config, wall_s: float) -> dict:
    """Achieved-bandwidth floor for a timed row (docs/PERF.md formula):
    every round re-reads and re-writes the persistent carry, so
    bytes-touched/round >= 2*carry_bytes and

        achieved >= 2 * carry_bytes * n_rounds / wall_s.

    A FLOOR: round temporaries, multi-pass sorts and collective traffic
    only add bytes, so hbm_peak_frac understates how bandwidth-bound a
    config is — useful as a denominator, never as a brag."""
    nbytes = carry_nbytes(cfg)
    achieved = 2.0 * nbytes * cfg.n_rounds / wall_s if wall_s > 0 else 0.0
    return {"carry_bytes": nbytes,
            "bytes_per_round_floor": 2 * nbytes,
            "achieved_gbps_floor": round(achieved / 1e9, 3),
            "hbm_peak_frac_floor": round(achieved / (HBM_PEAK_GBPS * 1e9),
                                         4),
            "hbm_peak_gbps": HBM_PEAK_GBPS}


def time_tpu(cfg: Config, repeats: int = 3) -> dict:
    """Time the round loop on device. runner.run_device's completion
    barrier is the O(1)-byte `_sync_elem` witness: a jitted 1-element
    slice of a final-carry leaf whose 4 bytes reaching the host prove
    the whole scan finished (pulling a full extract leaf measured the
    tunnel, ~100 MB for paxos, and block_until_ready returns early on
    the tunnel backend — docs/PERF.md round 5). The full decided logs
    are pulled once, OUTSIDE the timed window, for the digest.

    Every timed repeat runs under a DIFFERENT seed vector (base seed
    offset by (r+1)*n_sweeps, so no sweep repeats any trajectory
    already dispatched): the tunnel backend caches identical
    dispatches (PERF.md round 5), so re-dispatching byte-identical
    inputs could replay a cached result and overstate steps/sec. The
    kernels are branchless with seed-independent shapes, so per-seed
    work — and therefore throughput — is identical across repeats. The
    digest comes from the kept warmup carry at the base seed (same
    compiled program the repeats time), keeping it comparable with the
    oracle rows; the kept carry raises peak device memory by one carry.
    """
    import numpy as np

    from consensus_tpu.core import serialize
    from consensus_tpu.network import runner, simulator
    from consensus_tpu.obs import metrics as obs_metrics
    eng = simulator.engine_def(cfg)
    warm_carry = runner.run_device(cfg, eng)  # compile + warm; base seed
    # Per-config metrics delta: reset AFTER the warmup so the embedded
    # dispatch histogram covers only the timed repeats — the per-chunk
    # breakdown (dispatch vs checkpoint IO) each BENCH row finally
    # carries alongside its totals (docs/OBSERVABILITY.md).
    obs_metrics.reset()
    best = float("inf")
    for rep in range(repeats):
        seeds = runner.make_seeds(dataclasses.replace(
            cfg, seed=cfg.seed + (rep + 1) * cfg.n_sweeps))
        t0 = time.perf_counter()
        runner.run_device(cfg, eng, seeds=seeds)
        best = min(best, time.perf_counter() - t0)
    metrics_snap = obs_metrics.snapshot()
    # Digest epilogue: extract from the warmup carry (base seed) — the
    # digest validates the same compiled kernel the repeats timed.
    out = {k: np.asarray(v) for k, v in eng.extract(warm_carry).items()}
    _, _, _, payload = simulator.decided_payload(cfg, out)
    steps = cfg.n_sweeps * cfg.n_nodes * cfg.n_rounds
    return {"engine": "tpu", "config": json.loads(cfg.to_json()),
            "steps": steps, "wall_s": best, "steps_per_sec": steps / best,
            "bandwidth": bandwidth_stats(cfg, best),
            "digest": serialize.digest(payload),
            "metrics": metrics_snap}


def time_tpu_repeat_scan(cfg: Config, repeats: int = 8) -> dict:
    """Dispatch-bound configs (REPEAT_SCAN): all timed repeats inside ONE
    dispatch — a jitted ``lax.scan`` over repeat lanes, each lane a full
    independent run (fresh carry from its own per-repeat seed vector,
    offset (rep+1)·n_sweeps like time_tpu, then the same per-round
    ``eng.round_fn`` scan the plain path times). The scan serializes the
    lanes, so one dispatch's wall covers ``repeats`` real runs and the
    per-run figure ``wall/repeats`` amortizes the dispatch+tunnel
    overhead that made separate sub-millisecond dispatches read ±30%
    run-to-run (the committed raft-5node rows). The compile/warmup call
    uses a DIFFERENT seed matrix (offsets shifted by ``repeats``) so the
    timed dispatch is never byte-identical to a prior one — the tunnel
    dispatch cache can't replay it (PERF.md round 5). Digest epilogue:
    a plain run_device at the base seed, same round kernel.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from consensus_tpu.core import serialize
    from consensus_tpu.network import runner, simulator
    from consensus_tpu.obs import metrics as obs_metrics
    assert not cfg.mesh_shape, "repeat-scan timing is single-device only"
    eng = simulator.engine_def(cfg)

    def seed_mat(base_off: int) -> np.ndarray:  # [repeats, n_sweeps] u32
        return np.stack([
            runner.make_seeds(dataclasses.replace(
                cfg, seed=cfg.seed + (base_off + rep + 1) * cfg.n_sweeps))
            for rep in range(repeats)])

    @jax.jit
    def repeat_scan(mat):
        def lane(carry, sv):
            c = jax.vmap(lambda s: eng.make_carry(cfg, s))(sv)
            xs = jnp.arange(cfg.n_rounds, dtype=jnp.int32)
            c, _ = jax.lax.scan(
                lambda cc, r: (jax.vmap(
                    lambda s: eng.round_fn(cfg, s, r))(cc), None), c, xs)
            # Per-lane O(1) witness element; returning it as the scan
            # output keeps every lane live (nothing for XLA to elide).
            return carry, jax.tree.leaves(c)[0].ravel()[0]
        _, w = jax.lax.scan(lane, jnp.uint32(0), mat)
        return w

    np.asarray(repeat_scan(seed_mat(repeats)))  # compile, distinct bytes
    obs_metrics.reset()
    t0 = time.perf_counter()
    np.asarray(repeat_scan(seed_mat(0)))  # witness vector = sync barrier
    dispatch_wall = time.perf_counter() - t0
    metrics_snap = obs_metrics.snapshot()

    # Digest epilogue at the base seed (outside the timed window).
    carry = runner.run_device(cfg, eng)
    out = {k: np.asarray(v) for k, v in eng.extract(carry).items()}
    _, _, _, payload = simulator.decided_payload(cfg, out)
    steps = cfg.n_sweeps * cfg.n_nodes * cfg.n_rounds  # per repeat lane
    wall = dispatch_wall / repeats
    return {"engine": "tpu", "config": json.loads(cfg.to_json()),
            "steps": steps, "wall_s": wall,
            "steps_per_sec": steps / wall,
            "timing": "repeat-scan-one-dispatch",
            "repeats_in_dispatch": repeats,
            "dispatch_wall_s": dispatch_wall,
            "bandwidth": bandwidth_stats(cfg, wall),
            "digest": serialize.digest(payload),
            "metrics": metrics_snap}


def time_oracle(cfg: Config, repeats: int = 2) -> dict:
    from consensus_tpu.network import simulator
    cfg = dataclasses.replace(cfg, engine="cpu")
    best = None
    for _ in range(repeats):
        r = simulator.run(cfg)
        if best is None or r.wall_s < best.wall_s:
            best = r
    return {"engine": "cpu-oracle", "config": json.loads(cfg.to_json()),
            "steps": best.node_round_steps, "wall_s": best.wall_s,
            "steps_per_sec": best.steps_per_sec, "digest": best.digest}


def bench_pbft_fsweep(fs, repeats: int = 3) -> dict:
    """BASELINE config 3 the TPU-native way: the whole f ladder as ONE
    compiled program (engines/pbft_sweep.py), not one compile per f.

    ``steps`` counts only real (3f+1) nodes — padded lanes are FLOP waste,
    not simulated work, so they may not inflate steps/sec. Compile time is
    reported separately (it is the cost the padding design amortizes).
    """
    from consensus_tpu.core import serialize
    from consensus_tpu.engines.pbft_sweep import (fsweep_payload,
                                                  pbft_fsweep_timed)

    f_max = max(fs)
    cfg = Config(protocol="pbft", f=f_max, n_nodes=3 * f_max + 1, n_rounds=32,
                 n_sweeps=1, log_capacity=32, seed=3, **ADV)
    out, compile_s, best, real_steps = pbft_fsweep_timed(cfg, fs,
                                                         repeats=repeats)
    assert any(o["committed"].any() for o in out), "f-sweep committed nothing"
    # Same digest the CLI's --f-sweep reports — one shared payload
    # definition (engines.pbft_sweep.fsweep_payload), so every
    # RESULTS.json row carries a comparable equivalence handle.
    payload = fsweep_payload(out)

    padded_steps = len(fs) * (3 * f_max + 1) * cfg.n_rounds
    return {"engine": "tpu", "fs": [int(f) for f in fs],
            "n_rounds": cfg.n_rounds, "log_capacity": cfg.log_capacity,
            "compile_s_one_program": compile_s,
            "steps": real_steps, "padded_steps": padded_steps,
            "wall_s": best, "steps_per_sec": real_steps / best,
            "digest": serialize.digest(payload)}


def bench_pbft_oracle_ladder(fs) -> list[dict]:
    out = []
    for f in fs:
        cfg = Config(protocol="pbft", f=f, n_nodes=3 * f + 1, n_rounds=32,
                     n_sweeps=1, log_capacity=32, seed=3, **ADV)
        row = {"name": f"pbft-f{f}", "oracle": time_oracle(cfg, repeats=1)}
        out.append(row)
        _progress(row)
    return out


def _progress(row: dict) -> None:
    t = row.get("tpu", {}).get("steps_per_sec", 0)
    o = row.get("oracle", {}).get("steps_per_sec", 0)
    speed = f" speedup={t / o:.1f}x" if o else ""
    print(f"  {row['name']:16s} tpu={t / 1e6:8.2f}M/s"
          + (f" oracle={o / 1e6:6.2f}M/s{speed}" if o else ""),
          file=sys.stderr, flush=True)


def stale_rows(doc: dict) -> list[tuple[str, str]]:
    """(name, note) for every row carrying a ``stale_timing`` marker —
    a committed measurement known to predate a timing or kernel fix
    (the pbft-100k-bcast row predates both the repeat-scan fix and the
    sort-diet round). A fresh measurement of the config naturally drops
    the marker (the row is rebuilt); until then every reader of the
    file is warned up front."""
    return [(row["name"], row["stale_timing"])
            for row in doc.get("rows", []) if row.get("stale_timing")]


def warn_stale(path: pathlib.Path) -> None:
    if not path.exists():
        return
    try:
        doc = json.loads(path.read_text())
    except ValueError:
        return
    for name, note in stale_rows(doc):
        print(f"  STALE ROW {name}: {note}", file=sys.stderr, flush=True)


def backfill_bandwidth(path: pathlib.Path) -> int:
    """Add the achieved-bandwidth column to existing RESULTS rows from
    their recorded config + wall (pure arithmetic over the state schema
    — no device run, so committed on-chip walls keep their provenance).
    Returns the number of rows updated."""
    doc = json.loads(path.read_text())
    n = 0
    for row in doc.get("rows", []):
        tpu = row.get("tpu")
        if not tpu or "wall_s" not in tpu or "config" not in tpu:
            continue  # oracle-only rows and the padded f-sweep program
        cfg = Config.from_json(json.dumps(tpu["config"]))
        tpu["bandwidth"] = bandwidth_stats(cfg, tpu["wall_s"])
        n += 1
    path.write_text(json.dumps(doc, indent=2))
    return n


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="small pbft ladder, fewer repeats")
    ap.add_argument("--backfill-bandwidth", action="store_true",
                    help="no benchmark runs: recompute the bandwidth "
                         "column for every TPU row already in the output "
                         "JSON (state-schema arithmetic over recorded "
                         "walls) and rewrite the file")
    ap.add_argument("--skip-oracle", action="store_true")
    ap.add_argument("--skip-tpu", action="store_true",
                    help="oracle baseline only (no JAX engine runs) — used "
                         "to produce the BASELINE.md single-core numbers "
                         "when no accelerator is reachable")
    ap.add_argument("--only", default="",
                    help="comma-separated subset of config names")
    ap.add_argument("--out", default="",
                    help="output JSON path (default benchmarks/RESULTS.json)")
    ap.add_argument("--platform", default="auto",
                    choices=["auto", "cpu", "tpu"],
                    help="JAX backend for the engine rows (hang-proof "
                         "probe; see consensus_tpu.utils.platform)")
    args = ap.parse_args()

    out_path = pathlib.Path(args.out) if args.out else \
        pathlib.Path(__file__).parent / "RESULTS.json"
    warn_stale(out_path)

    if args.backfill_bandwidth:
        n = backfill_bandwidth(out_path)
        print(f"bandwidth column backfilled on {n} rows in {out_path}",
              file=sys.stderr)
        return

    if args.skip_tpu:
        results = {"device": "none (oracle only)", "platform": "cpu-oracle",
                   "timestamp": time.time(), "rows": []}
    else:
        from consensus_tpu.utils.platform import ensure_platform
        tag = ensure_platform(args.platform)
        import jax
        dev = jax.devices()[0]
        print(f"benchmarks: device={dev} platform={dev.platform} ({tag})",
              file=sys.stderr)
        results = {"device": str(dev), "platform": tag,
                   "timestamp": time.time(), "rows": []}
    only = set(args.only.split(",")) if args.only else None

    for name, cfg in CONFIGS.items():
        if only and name not in only:
            continue
        row = {"name": name}
        if not args.skip_tpu:
            row["tpu"] = (time_tpu_repeat_scan(cfg) if name in REPEAT_SCAN
                          else time_tpu(cfg))
        if not args.skip_oracle:
            row["oracle"] = time_oracle(
                ORACLE_SIZED.get(name, cfg),
                repeats=1 if name in ORACLE_ONE_REPEAT else 2)
        results["rows"].append(row)
        _progress(row)

    if not only or any(n.startswith("pbft-fsweep") for n in only):
        if not args.skip_tpu:
            # The measured artifact for BASELINE config 3: the FULL f=1..128
            # ladder in one compiled program ([--quick]: power-of-two rungs).
            fs = PBFT_FS[:4] if args.quick else list(range(1, 129))
            row = {"name": "pbft-fsweep-one-program",
                   "tpu": bench_pbft_fsweep(fs)}
            results["rows"].append(row)
            _progress(row)
        if not args.skip_oracle:
            # Per-f scalar oracle rungs for the speedup denominator.
            fs = PBFT_FS[:4] if args.quick else PBFT_FS
            results["rows"] += bench_pbft_oracle_ladder(fs)

    out_path.write_text(json.dumps(results, indent=2))
    print(f"wrote {out_path}", file=sys.stderr)


if __name__ == "__main__":
    main()
