"""CLI front-door tests (SURVEY.md §2 component 13).

The native `cpp/consensus-sim` binary and `python -m consensus_tpu` must
report the *same digest* for the same flags — that is the reference's
engine-pluggable seam made observable: one CLI, two engines, byte-equal
decided logs (BASELINE.json:2,5).
"""
import hashlib
import json
import pathlib
import subprocess

import pytest

from consensus_tpu import cli

CPP_DIR = pathlib.Path(__file__).resolve().parents[1] / "cpp"
SIM = CPP_DIR / "consensus-sim"

FLAG_SETS = {
    "raft": ["--protocol", "raft", "--nodes", "5", "--rounds", "64",
             "--sweeps", "2", "--log-capacity", "32", "--max-entries", "20",
             "--drop-rate", "0.1", "--churn-rate", "0.05"],
    "pbft": ["--protocol", "pbft", "--f", "1", "--rounds", "24",
             "--log-capacity", "8", "--drop-rate", "0.1"],
    "paxos": ["--protocol", "paxos", "--nodes", "7", "--rounds", "24",
              "--log-capacity", "8", "--drop-rate", "0.1"],
    "dpos": ["--protocol", "dpos", "--nodes", "24", "--rounds", "32",
             "--log-capacity", "48", "--candidates", "8", "--producers", "3",
             "--epoch-len", "8", "--drop-rate", "0.1"],
}


def _build_sim():
    subprocess.run(["make", "-C", str(CPP_DIR), "-s", "consensus-sim"],
                   check=True)


def _run_native(flags, extra=()):
    _build_sim()
    out = subprocess.run([str(SIM), *flags, *extra], check=True,
                         capture_output=True, text=True)
    return json.loads(out.stdout)


@pytest.mark.parametrize("proto", list(FLAG_SETS))
def test_native_cli_digest_matches_tpu_engine(proto, capsys):
    native = _run_native(FLAG_SETS[proto])
    # TPU engine in-process (pytest runs on the virtual CPU mesh backend,
    # same jit code path as the chip).
    rc = cli.main(FLAG_SETS[proto] + ["--engine", "tpu"])
    assert rc == 0
    ours = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert native["digest"] == ours["digest"], (native, ours)
    assert native["payload_bytes"] == ours["payload_bytes"]


def test_native_sha256_matches_hashlib(tmp_path):
    payload = tmp_path / "p.bin"
    native = _run_native(FLAG_SETS["raft"], extra=["--out", str(payload)])
    data = payload.read_bytes()
    assert len(data) == native["payload_bytes"]
    assert hashlib.sha256(data).hexdigest() == native["digest"]


def test_python_cli_cpu_engine_matches_native(capsys):
    native = _run_native(FLAG_SETS["paxos"])
    rc = cli.main(FLAG_SETS["paxos"] + ["--engine", "cpu"])
    assert rc == 0
    ours = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert native["digest"] == ours["digest"]


def test_cli_mesh_flag(capsys):
    rc = cli.main(FLAG_SETS["raft"] + ["--engine", "tpu", "--mesh", "2x1"])
    assert rc == 0
    sharded = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    native = _run_native(FLAG_SETS["raft"])
    assert sharded["digest"] == native["digest"]


def test_cli_config_file_values_survive(tmp_path, capsys):
    # A --config file must fully drive the run; only flags the user
    # actually types may override it (review finding: argparse defaults
    # were stomping every file value).
    cfgfile = tmp_path / "cfg.json"
    args = cli.build_parser().parse_args(FLAG_SETS["raft"] + ["--engine", "cpu"])
    cfg = cli.args_to_config(args)
    cfgfile.write_text(cfg.to_json())
    rc = cli.main(["--config", str(cfgfile)])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    native = _run_native(FLAG_SETS["raft"])
    assert out["digest"] == native["digest"]
    assert out["engine"] == "cpu" and out["n_rounds"] == 64


def test_cli_fsweep_digest_matches_per_f_runs(capsys):
    """--f-sweep (one padded compiled program) must serialize byte-equal to
    running each f alone: element k == a single-sweep run with f=fs[k],
    seed=seed+k (engines/pbft_sweep.py's padding contract, VERDICT r1 #5)."""
    fs = [1, 2, 4]
    base = ["--protocol", "pbft", "--rounds", "24", "--log-capacity", "8",
            "--drop-rate", "0.1", "--seed", "7"]
    rc = cli.main(base + ["--engine", "tpu", "--f-sweep", "1,2,4"])
    assert rc == 0
    sweep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])

    expected = b""
    import dataclasses

    from consensus_tpu.core.config import Config
    from consensus_tpu.network import simulator
    for k, f in enumerate(fs):
        cfg = Config(protocol="pbft", f=f, n_nodes=3 * f + 1, n_rounds=24,
                     log_capacity=8, drop_rate=0.1, seed=7 + k)
        expected += simulator.run(cfg, warmup=False).payload
    import hashlib as h
    assert sweep["digest"] == h.sha256(expected).hexdigest()
    assert sweep["payload_bytes"] == len(expected)
    assert sweep["steps"] == sum(3 * f + 1 for f in fs) * 24


def test_cli_fsweep_schema_stable(capsys):
    """The --f-sweep JSON report is machine-consumed (benchmarks, the
    driver); its key set is a frozen schema (VERDICT r3 #6)."""
    rc = cli.main(["--protocol", "pbft", "--rounds", "8", "--log-capacity",
                   "8", "--engine", "tpu", "--f-sweep", "1,2"])
    assert rc == 0
    sweep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert set(sweep) == {
        "protocol", "engine", "platform", "f_sweep", "n_elements",
        "n_rounds", "n_sweeps", "fault_model", "seed", "steps", "wall_s",
        "steps_per_sec", "compile_s_one_program", "payload_bytes",
        "rung_digests", "digest"}
    assert sweep["n_elements"] == 2 and len(sweep["digest"]) == 64
    assert len(sweep["rung_digests"]) == 2
    assert sweep["compile_s_one_program"] > 0


def test_cli_profile_writes_trace(tmp_path, capsys):
    """--profile must produce a non-empty jax.profiler trace directory and
    leave the decided-log digest untouched (VERDICT r3 #6: this path had
    never been executed)."""
    tdir = tmp_path / "trace"
    rc = cli.main(FLAG_SETS["raft"] + ["--engine", "tpu",
                                       "--profile", str(tdir)])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    traced = list(tdir.rglob("*"))
    assert any(f.is_file() for f in traced), "no trace files written"
    rc = cli.main(FLAG_SETS["raft"] + ["--engine", "tpu"])
    assert rc == 0
    plain = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["digest"] == plain["digest"]


def test_cli_fsweep_requires_pbft_tpu():
    with pytest.raises(SystemExit):
        cli.main(["--protocol", "raft", "--engine", "tpu",
                  "--f-sweep", "1..4"])


def test_cli_fsweep_bcast_ladder_matches_individual_runs(capsys):
    """The lifted carve-outs (VERDICT weak #5): a `--fault-model bcast
    --f-sweep 1,2,4 --sweeps 2` ladder runs as ONE compiled padded
    program whose per-rung digests equal standalone runs through BOTH
    front doors — the Python CLI's tpu engine and the native binary's
    cpu oracle (f=fs[k], seed=seed+k, n_sweeps=2 each)."""
    fs = [1, 2, 4]
    base = ["--protocol", "pbft", "--fault-model", "bcast", "--rounds",
            "24", "--log-capacity", "8", "--drop-rate", "0.1",
            "--partition-rate", "0.05", "--sweeps", "2", "--seed", "7"]
    rc = cli.main(base + ["--engine", "tpu", "--f-sweep", "1,2,4"])
    assert rc == 0
    sweep = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert sweep["fault_model"] == "bcast" and sweep["n_sweeps"] == 2

    expected = b""
    for k, f in enumerate(fs):
        rung = ["--protocol", "pbft", "--fault-model", "bcast", "--f",
                str(f), "--rounds", "24", "--log-capacity", "8",
                "--drop-rate", "0.1", "--partition-rate", "0.05",
                "--sweeps", "2", "--seed", str(7 + k)]
        rc = cli.main(rung + ["--engine", "tpu"])
        assert rc == 0
        ours = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert sweep["rung_digests"][k] == ours["digest"], (k, f)
        # Same rung through the native front door (cpu oracle engine).
        native = _run_native(rung)
        assert sweep["rung_digests"][k] == native["digest"], (k, f)
        from consensus_tpu.core.config import Config
        from consensus_tpu.network import simulator
        cfg = Config(protocol="pbft", fault_model="bcast", f=f,
                     n_nodes=3 * f + 1, n_rounds=24, log_capacity=8,
                     drop_rate=0.1, partition_rate=0.05, n_sweeps=2,
                     seed=7 + k)
        expected += simulator.run(cfg, warmup=False).payload
    assert sweep["digest"] == hashlib.sha256(expected).hexdigest()
    assert sweep["payload_bytes"] == len(expected)
    assert sweep["steps"] == sum(3 * f + 1 for f in fs) * 24 * 2


def test_cli_fsweep_rejects_byz_above_smallest_rung():
    # A rung below n_byzantine has no valid standalone twin (pbft
    # requires n_byzantine <= f) — fail in arg validation, not later.
    with pytest.raises(SystemExit):
        cli.main(["--protocol", "pbft", "--engine", "tpu", "--f", "2",
                  "--n-byzantine", "2", "--f-sweep", "1,2,4"])


def test_cli_rejects_tpu_flags_on_cpu_engine():
    with pytest.raises(SystemExit):
        cli.main(FLAG_SETS["raft"] + ["--engine", "cpu", "--mesh", "2x1"])


def test_cli_rejects_checkpoint_with_sweep_chunk(tmp_path):
    # Must die in arg validation (clean parser.error), not as a raw
    # ValueError from runner.run after the accelerator probe.
    with pytest.raises(SystemExit):
        cli.main(FLAG_SETS["raft"] + ["--engine", "tpu", "--sweeps", "4",
                                      "--sweep-chunk", "2",
                                      "--checkpoint", str(tmp_path / "c")])


def test_cli_typed_flag_overrides_config_file(tmp_path, capsys):
    cfgfile = tmp_path / "cfg.json"
    args = cli.build_parser().parse_args(FLAG_SETS["raft"] + ["--engine", "cpu"])
    cfgfile.write_text(cli.args_to_config(args).to_json())
    rc = cli.main(["--config", str(cfgfile), "--seed", "9"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["seed"] == 9
    assert out["n_rounds"] == 64  # untyped flag: file value survives
