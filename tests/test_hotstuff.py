"""Chained HotStuff (SPEC §7b): differential byte-equivalence across
the adversary surface, pipeline/liveness invariants, and the
linear-communication claims the engine exists for."""
import dataclasses

import numpy as np
import pytest

from consensus_tpu import Config
from consensus_tpu.network import simulator

from helpers import run_cached

BASE = Config(protocol="hotstuff", f=2, n_nodes=7, n_rounds=96,
              n_sweeps=3, log_capacity=96, seed=3)
CFGS = [
    BASE,
    # Composed delivery faults: drops + partitions + churned leaders.
    dataclasses.replace(BASE, drop_rate=0.2, churn_rate=0.05,
                        partition_rate=0.1, seed=1),
    # §6c crash-recover + §A.2 delayed retransmission composed.
    dataclasses.replace(BASE, drop_rate=0.2, crash_prob=0.1,
                        recover_prob=0.3, max_crashed=2,
                        max_delay_rounds=3, seed=2),
    # Silent byzantine minority at a larger population (f=10, N=31):
    # Q = 2f+1 quorums must still form from the honest 2f+1+... under
    # light loss.
    dataclasses.replace(BASE, f=10, n_nodes=31, n_byzantine=7,
                        drop_rate=0.05, churn_rate=0.02, seed=5),
    # Mid-size shape (N = 301): leader ids wrap the population several
    # times; everything composed.
    dataclasses.replace(BASE, f=100, n_nodes=301, drop_rate=0.1,
                        partition_rate=0.05, churn_rate=0.01,
                        crash_prob=0.05, recover_prob=0.3,
                        max_crashed=10, max_delay_rounds=2, seed=7),
    # SPEC §B view desync composed with drops (drops keep the healed
    # views apart) — the premature-timeout path, gossip catch-up, and
    # per-receiver leader identity all live.
    dataclasses.replace(BASE, desync_rate=0.15, max_skew_rounds=4,
                        view_timeout=4, drop_rate=0.25, seed=11),
    # §B + §6c + §7c together: crash recovery resets a node's view to 0
    # while skew pushes others ahead — maximal view spread.
    dataclasses.replace(BASE, f=10, n_nodes=31, n_byzantine=7,
                        byz_mode="equivocate", desync_rate=0.1,
                        max_skew_rounds=3, view_timeout=4, drop_rate=0.15,
                        crash_prob=0.05, recover_prob=0.3, max_crashed=3,
                        seed=13),
    # Big-N synchronizer parity (N = 1024 <= 2k): leader wrap + gossip
    # min-id tie-break at scale, desync composed with delivery faults.
    dataclasses.replace(BASE, f=341, n_nodes=1024, n_rounds=32,
                        n_sweeps=1, log_capacity=32, desync_rate=0.1,
                        max_skew_rounds=4, view_timeout=4, drop_rate=0.1,
                        partition_rate=0.05, seed=17),
]


@pytest.mark.parametrize("cfg", CFGS)
def test_hotstuff_decided_log_byte_equivalence(cfg):
    tpu = run_cached(cfg)
    cpu = run_cached(dataclasses.replace(cfg, engine="cpu"))
    assert tpu.payload == cpu.payload, (tpu.digest, cpu.digest)


def test_hotstuff_config_shape_and_byz_rules():
    with pytest.raises(ValueError, match="3f\\+1"):
        dataclasses.replace(BASE, n_nodes=8)
    with pytest.raises(ValueError, match="n_byzantine"):
        dataclasses.replace(BASE, n_byzantine=3)  # > f = 2
    # SPEC §7c: equivocation is a real hotstuff mode now — a byzantine
    # leader proposes two block variants and the engine keeps per-value
    # QC tallies (the former counts-only rejection is lifted).
    cfg = dataclasses.replace(BASE, n_byzantine=1, byz_mode="equivocate")
    assert cfg.byz_mode == "equivocate"
    # bcast is the §6b pbft fault model; hotstuff delivery is already a
    # star of O(N) edges.
    with pytest.raises(ValueError, match="bcast"):
        dataclasses.replace(BASE, fault_model="bcast")


def test_hotstuff_faultfree_commits_one_block_per_round():
    """The chained-pipeline claim: with no faults every round forms a
    QC, so after the 3-deep pipeline fills, the global chain commits
    exactly one block per round (gcommit = rounds - pipeline depth)."""
    res = run_cached(BASE)
    # Every node's committed prefix: length >= n_rounds - depth - 1
    # (the last commit is learned one round after it happens).
    counts = res.counts
    assert counts.min() >= BASE.n_rounds - 4
    assert counts.max() <= BASE.n_rounds  # never more than one per round


def test_hotstuff_committed_prefixes_agree_and_match_chain():
    """Safety across nodes: every pair of committed prefixes agrees
    (the chained 3-chain rule admits one block per height), and each
    decided value is the SPEC §7b counter function of its certifying
    view."""
    from consensus_tpu.engines.hotstuff import HotstuffState  # noqa: F401
    from helpers import committed_prefixes_agree
    cfg = CFGS[1]
    res = run_cached(cfg)
    for b in range(cfg.n_sweeps):
        assert committed_prefixes_agree(res, list(range(cfg.n_nodes)), b)
        # Records are (height, value) with heights a dense prefix.
        for n in range(cfg.n_nodes):
            c = int(res.counts[b, n])
            assert list(res.rec_a[b, n, :c]) == list(range(c))


def test_hotstuff_view_timeout_bounds_leader_outage():
    """A dead leader costs at most view_timeout rounds: with every
    delivery fault off but heavy §6c churn capped at 1 down node,
    commits keep flowing (availability, not safety, is what crashes
    attack)."""
    cfg = dataclasses.replace(BASE, crash_prob=0.3, recover_prob=0.5,
                              max_crashed=1, view_timeout=4, seed=9)
    res = run_cached(cfg)
    cpu = run_cached(dataclasses.replace(cfg, engine="cpu"))
    assert res.payload == cpu.payload
    # Liveness: the run still commits a sizable chain.
    assert res.counts.sum() > 0
    assert (res.counts.max(axis=1) >= cfg.n_rounds // 4).all()


def test_hotstuff_telemetry_digest_neutral_and_consistent():
    """Telemetry counters never change the trajectory, and the QC /
    commit counters agree with the decided logs."""
    cfg = CFGS[1]
    stats: dict = {}
    res = simulator.run(cfg, warmup=False, telemetry=True, stats=stats)
    assert res.payload == run_cached(cfg).payload
    tel = stats["telemetry"]
    # Commits learned == total decided records (every record was
    # learned exactly once).
    assert int(tel["commits_learned"].sum()) == int(res.counts.sum())
    # The pipeline can never commit more blocks than QCs formed.
    assert (tel["blocks_committed"] <= tel["qc_formed"]).all()
    # Fault-free sweep-level sanity on the flight recorder path.
    stats2: dict = {}
    cfg2 = dataclasses.replace(cfg, telemetry_window=8)
    res2 = simulator.run(cfg2, warmup=False, telemetry=True, stats=stats2)
    assert res2.payload == res.payload  # recorder is digest-neutral
    fl = stats2["flight"]
    assert set(fl["latency"]) == {"view_change_wait_rounds",
                                  "chain_commit_lag_rounds"}


def test_hotstuff_round_carry_is_o_n_plus_s():
    """The linear-communication claim at the state level: no carry leaf
    is [N, S]-shaped — per-node state is O(N) vectors, the chain map is
    O(S); the [N, S] decided tensors exist only in the extraction
    epilogue."""
    import jax

    from consensus_tpu.engines.hotstuff import hotstuff_init
    tpl = jax.eval_shape(lambda s: hotstuff_init(BASE, s),
                         jax.ShapeDtypeStruct((), np.uint32))
    for leaf in jax.tree.leaves(tpl):
        assert len(leaf.shape) <= 1, leaf.shape


def test_hotstuff_oracle_rejects_delivery_knob():
    with pytest.raises(ValueError, match="oracle_delivery"):
        simulator.run(dataclasses.replace(BASE, engine="cpu"),
                      warmup=False, oracle_delivery="dense")


@pytest.mark.slow
def test_hotstuff_flagship_digest_pair():
    """The acceptance criterion at true shape: hotstuff-100k
    byte-matches the C++ oracle twin (edge-wise star delivery makes the
    oracle seconds-class at N = 100k — docs/PERF.md)."""
    from benchmarks.run_benchmarks import CONFIGS
    cfg = CONFIGS["hotstuff-100k"]
    tpu = simulator.run(cfg, warmup=False)
    cpu = simulator.run(dataclasses.replace(cfg, engine="cpu"),
                        warmup=False)
    assert tpu.payload == cpu.payload, (tpu.digest, cpu.digest)


# --- SPEC §B per-node view synchronizer vs the retired global pacemaker -----
#
# The synchronizer's sync path must reproduce the retired one-scalar
# pacemaker — kept verbatim as a test-only reference
# (tests/reference_hotstuff.py) — wherever views stay in lockstep: zero
# delivery-fault rates (drops/partitions/crashes are exactly what the
# per-node model lets desynchronize views), with churn and both
# byzantine modes composed (those stall every node identically). The
# mapping: production per-node view[i] == retired GLOBAL gview for all
# i, every other leaf byte-equal, every counter except view_changes
# equal (a timeout is now N per-node advances, not one global one).

LOCKSTEP_CONFIGS = [
    ("faultfree", BASE),
    ("churn", dataclasses.replace(BASE, churn_rate=0.3, seed=1)),
    ("byz-silent", dataclasses.replace(BASE, f=10, n_nodes=31,
                                       n_byzantine=7, seed=5)),
    ("byz-equiv", dataclasses.replace(BASE, n_byzantine=2,
                                      byz_mode="equivocate",
                                      churn_rate=0.1, seed=7)),
    ("switch-equiv", dataclasses.replace(BASE, n_byzantine=2,
                                         byz_mode="equivocate",
                                         net_model="switch",
                                         n_aggregators=2, seed=9)),
]


@pytest.mark.parametrize("tag,cfg", LOCKSTEP_CONFIGS,
                         ids=[t for t, _ in LOCKSTEP_CONFIGS])
def test_synchronizer_bit_identical_to_retired_pacemaker(tag, cfg):
    import pathlib
    import sys
    sys.path.insert(0, str(pathlib.Path(__file__).parent))
    from reference_hotstuff import reference_engine

    from consensus_tpu.engines import hotstuff
    from consensus_tpu.network import runner

    new_stats, ref_stats = {}, {}
    new = runner.run(cfg, hotstuff.get_engine(), stats=new_stats,
                     telemetry=True)
    ref = runner.run(cfg, reference_engine(), stats=ref_stats,
                     telemetry=True)
    for key in new:
        if key == "view":
            want = np.broadcast_to(ref["gview"][..., None],
                                   new["view"].shape)
        else:
            want = ref[key]
        np.testing.assert_array_equal(new[key], want, err_msg=(tag, key))
    for name, vals in ref_stats["telemetry"].items():
        if name == "view_changes":
            continue
        np.testing.assert_array_equal(new_stats["telemetry"][name], vals,
                                      err_msg=(tag, name))


def test_desync_skew_fires_premature_timeouts():
    """SPEC §B STREAM_DESYNC end to end: a zero-rate config is
    bit-identical to the default program, and a hot desync composed
    with drop desynchronizes end-of-round views (nonzero spread), fires
    premature view changes, and drives sync traffic — the counters the
    view-desync-storm scenario gates on."""
    stats: dict = {}
    base = dataclasses.replace(BASE, view_timeout=4)
    res0 = simulator.run(base, warmup=False)
    resz = simulator.run(dataclasses.replace(base, desync_rate=0.0),
                         warmup=False)
    assert res0.payload == resz.payload
    hot = dataclasses.replace(base, desync_rate=0.15, max_skew_rounds=4,
                              drop_rate=0.25)
    res1 = simulator.run(hot, warmup=False, telemetry=True, stats=stats)
    assert res1.payload != res0.payload
    tel = stats["telemetry"]
    assert int(tel["view_spread_max"].sum()) > 0
    assert int(tel["desync_rounds"].sum()) > 0
    assert int(tel["sync_msgs_delivered"].sum()) > 0
    assert int(tel["view_changes"].sum()) > 0


def test_desync_knob_validation():
    with pytest.raises(ValueError, match="desync_rate"):
        Config(protocol="raft", f=2, n_nodes=7, desync_rate=0.1)
    with pytest.raises(ValueError, match="max_skew_rounds"):
        dataclasses.replace(BASE, desync_rate=0.1, max_skew_rounds=9)
    with pytest.raises(ValueError, match="max_skew_rounds"):
        dataclasses.replace(BASE, max_skew_rounds=2)
