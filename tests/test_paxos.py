"""Paxos: differential byte-equivalence + agreement invariant (SPEC §5)."""
import dataclasses

import numpy as np
import pytest

from consensus_tpu import Config
from consensus_tpu.network import simulator

from helpers import run_cached

BASE = Config(protocol="paxos", n_nodes=7, n_rounds=64, log_capacity=16,
              n_sweeps=4, seed=555)
CFGS = [
    BASE,
    dataclasses.replace(BASE, drop_rate=0.25, seed=1),
    dataclasses.replace(BASE, partition_rate=0.3, seed=2),
    dataclasses.replace(BASE, churn_rate=0.15, seed=3),
    dataclasses.replace(BASE, n_nodes=9, drop_rate=0.3, partition_rate=0.2,
                        churn_rate=0.1, n_rounds=96, seed=4),
    dataclasses.replace(BASE, n_proposers=3, drop_rate=0.2, seed=5),
]


@pytest.mark.parametrize("cfg", CFGS)
def test_paxos_decided_log_byte_equivalence(cfg):
    tpu = run_cached(cfg)
    cpu = run_cached(dataclasses.replace(cfg, engine="cpu"))
    assert tpu.payload == cpu.payload, (tpu.digest, cpu.digest)


@pytest.mark.parametrize("cfg", CFGS)
def test_paxos_agreement_per_slot(cfg):
    """Safety: at most one value is ever learned per slot across all nodes."""
    from consensus_tpu.engines.paxos import paxos_run
    out = paxos_run(cfg)
    mask, val = out["learned_mask"], out["learned_val"]
    for b in range(cfg.n_sweeps):
        for s in range(cfg.log_capacity):
            learners = mask[b, :, s]
            if learners.any():
                vals = np.unique(val[b, learners, s])
                assert vals.size == 1, f"sweep {b} slot {s}: {vals}"


def test_paxos_progress_clean():
    res = run_cached(BASE)
    # Clean network: every slot should be decided well within 64 rounds.
    assert res.counts.max() == BASE.log_capacity
