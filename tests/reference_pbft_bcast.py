"""The RETIRED pre-diet §6b round, kept verbatim as a test-only
reference (PR "sort-diet", ISSUE 8).

This is the engines/pbft_bcast.py kernel as committed before the
aggregate sort-diet: the §2 partition-side statistics come from a full
batched `jnp.sort`, and the P4/P5 tallies run through `_SortedTally` —
one payload sort carrying a permutation + flags, per-position counts
off cumsum/cummax/cummin brackets, and ONE unsort (a second payload
sort) returning results to node order. Three compiled sort passes per
round; the production round now compiles to ONE (docs/PERF.md).

Two jobs:

  * bit-identity oracle — tests/test_pbft_bcast.py drives this round
    and the production round through the SAME runner across the
    adversary grid (drops, partitions, churn, byz silent/equivocate,
    §6c crash) and asserts every extracted state leaf and telemetry
    counter is identical;
  * negative fixture — compiled through the production chunk jit it
    EXCEEDS the lowered `PROGRAM_CONTRACT` ceilings (3 sorts > 1,
    30 scan-class brackets > 20), proving the tightened sort-diet
    ceiling fires on precisely the program it retired
    (tests/test_hlocheck.py).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from consensus_tpu.core import rng
from consensus_tpu.core.config import Config
from consensus_tpu.engines.pbft import PBFT_TELEMETRY, PbftState, pbft_init
from consensus_tpu.engines.pbft_bcast import _extract, _pspec
from consensus_tpu.network.runner import EngineDef
from consensus_tpu.ops.aggregate import agg_counts
from consensus_tpu.ops.adversary import (crash_counts, crash_transition,
                                         freeze_down, safety_counts)
from consensus_tpu.ops.adversary import draw as _draw
from consensus_tpu.ops.adversary import cutoff as _lt
from consensus_tpu.ops.adversary import bitcast_i32 as _i32
from consensus_tpu.ops.viewsync import sync_counts

I32_MAX = jnp.iinfo(jnp.int32).max
I32_MIN = jnp.iinfo(jnp.int32).min


class _SortedTally:
    """Exact multiset counter, entirely in sorted space (retired): one
    payload sort up front carrying the permutation + flags, counts from
    the monotone cumsum bracketed at run boundaries, ONE unsort (a
    second payload sort keyed on the permutation) returning results."""

    def __init__(self, vals_sn, bits_sn, extra_sn=None):
        S, N = vals_sn.shape
        iota = jnp.broadcast_to(jnp.arange(N, dtype=jnp.int32), (S, N))
        ops = (vals_sn, iota, bits_sn) + \
            (() if extra_sn is None else (extra_sn,))
        srt = jax.lax.sort(ops, dimension=1, num_keys=1)
        self.sv, self.perm, self.bits = srt[0], srt[1], srt[2]
        self.extra = srt[3] if extra_sn is not None else None
        brk = self.sv[:, 1:] != self.sv[:, :-1]
        self.newrun = jnp.concatenate([jnp.ones((S, 1), bool), brk], axis=1)
        self.endrun = jnp.concatenate([brk, jnp.ones((S, 1), bool)], axis=1)

    def bit(self, k):
        return ((self.bits >> k) & 1).astype(bool)

    def count(self, valid_sn_sorted):
        f = valid_sn_sorted.astype(jnp.int32)
        s = jnp.cumsum(f, axis=1)
        ex_start = jax.lax.cummax(jnp.where(self.newrun, s - f, -1), axis=1)
        s_end = jax.lax.cummin(jnp.where(self.endrun, s, jnp.int32(2**30)),
                               axis=1, reverse=True)
        return s_end - ex_start

    def unsort(self, packed_sn):
        _, out = jax.lax.sort((self.perm, packed_sn), dimension=1,
                              num_keys=1)
        return out.T


def sorted_tally_round(cfg: Config, st: PbftState, r, *,
                       telem: bool = False):
    """The retired 3-sort round, verbatim."""
    N, S = cfg.n_nodes, cfg.log_capacity
    f = cfg.f
    Q = 2 * f + 1
    K = f + 1
    seed = st.seed
    ur = jnp.asarray(r, jnp.uint32)
    idx = jnp.arange(N, dtype=jnp.int32)
    uidx = idx.astype(jnp.uint32)
    sarange = jnp.arange(S, dtype=jnp.int32)

    no_part = cfg.partition_cutoff == 0
    bcast = rng.delivery_u32_jnp(seed, ur, uidx, uidx) >= _lt(cfg.drop_cutoff)
    crash_on = cfg.crash_cutoff > 0
    down = st.down
    if crash_on:
        down, rec, _crashed = crash_transition(
            seed, ur, down, cfg.crash_cutoff, cfg.recover_cutoff,
            cfg.max_crashed)
        up = ~down
        bcast = bcast & up
    if not no_part:
        part_active = (_draw(seed, rng.STREAM_PARTITION, ur, 0, 0)
                       < _lt(cfg.partition_cutoff))
        side = (_draw(seed, rng.STREAM_PARTITION, ur, 1, uidx)
                & jnp.uint32(1)).astype(jnp.int32)               # [N]
    churn = _draw(seed, rng.STREAM_CHURN, ur, 0, 0) < _lt(cfg.churn_cutoff)
    honest = idx < (N - cfg.n_byzantine)
    byz = ~honest

    def side_ok(b):
        return ~part_active | (side == b)

    equiv = cfg.byz_mode == "equivocate" and cfg.n_byzantine > 0

    view, timer = st.view, st.timer
    pp_seen, pp_view, pp_val = st.pp_seen, st.pp_view, st.pp_val
    prepared, committed, dval = st.prepared, st.committed, st.dval
    if crash_on:
        view = jnp.where(rec, 0, view)
        timer = jnp.where(rec, 0, timer)
        frozen = (view, timer, pp_seen, pp_view, pp_val, prepared,
                  committed, dval)
    committed_at_start = committed

    # ---- P0 churn.
    view = view + churn.astype(jnp.int32)
    timer = jnp.where(churn, 0, timer)
    reset = jnp.broadcast_to(churn, (N,))

    # ---- P1 view catch-up via the retired batched full sort.
    sender_v = honest & bcast
    if no_part:
        t = jnp.sort(jnp.where(sender_v, view, -1)[None, :], axis=1)
        a1 = jnp.broadcast_to(t[0, N - K], (N,))                 # [N]
        a2 = (jnp.broadcast_to(t[0, N - K + 1], (N,)) if K >= 2
              else jnp.full((N,), I32_MAX, jnp.int32))
    else:
        cols = jnp.stack([jnp.where(sender_v & side_ok(0), view, -1),
                          jnp.where(sender_v & side_ok(1), view, -1)])
        t = jnp.sort(cols, axis=1)                               # ascending
        a1 = t[:, N - K][side]                                   # [N]
        a2 = (t[:, N - K + 1] if K >= 2
              else jnp.full((2,), I32_MAX, jnp.int32))[side]
    in_set = sender_v                                            # self side ok
    vth = jnp.where(in_set, a1, jnp.clip(view, a1, a2))
    catch = vth > view
    view = jnp.where(catch, vth, view)
    timer = jnp.where(catch, 0, timer)
    reset |= catch

    # ---- P2 timeout.
    to = timer >= cfg.view_timeout
    view = view + to.astype(jnp.int32)
    timer = jnp.where(to, 0, timer)
    reset |= to

    # ---- P3 pre-prepare.
    is_primary = honest & (view % N == idx)
    fresh = jnp.min(jnp.where(~pp_seen, sarange[None, :], S), axis=1)
    fresh_hot = (sarange[None, :] == fresh[:, None])
    ppb = is_primary[:, None] & ((pp_seen & ~committed) | fresh_hot)
    fresh_val = _i32(_draw(seed, rng.STREAM_VALUE,
                           view[:, None].astype(jnp.uint32), 2,
                           sarange[None, :].astype(jnp.uint32)))
    msg_val = jnp.where(pp_seen, pp_val, fresh_val)

    prim = view % N
    if no_part:
        prim_del = (prim == idx) | bcast[prim]
    else:
        prim_del = (prim == idx) | (bcast[prim]
                                    & (~part_active | (side[prim] == side)))
    prim_ok = prim_del & (view[prim] == view)
    pm_b = ppb[prim]
    pm_val = msg_val[prim]
    if equiv:
        prim_byz = byz[prim]
        # Per-receiver fork (SPEC §7c): sup(r, prim(j), j) picks which
        # conflicting value the byz primary pre-prepares at receiver j.
        sup_prim = (_draw(seed, rng.STREAM_EQUIV, ur,
                          prim.astype(jnp.uint32), uidx)
                    & jnp.uint32(1)).astype(bool)
        bval = _i32(_draw(seed, rng.STREAM_VALUE,
                          view[:, None].astype(jnp.uint32),
                          jnp.where(sup_prim, 4, 3)[:, None]
                          .astype(jnp.uint32),
                          sarange[None, :].astype(jnp.uint32)))
        prim_ok = jnp.where(prim_byz, prim_del, prim_ok)
        pm_b = pm_b | prim_byz[:, None]
        pm_val = jnp.where(prim_byz[:, None], bval, pm_val)
    accept = (prim_ok[:, None] & pm_b
              & (~pp_seen | (pp_view < view[:, None]))
              & (~prepared | (pm_val == pp_val)))
    pp_view = jnp.where(accept, view[:, None], pp_view)
    pp_val = jnp.where(accept, pm_val, pp_val)
    pp_seen = pp_seen | accept

    # ---- P4 + P5 tallies in sorted space with the retired unsort.
    if equiv:
        # Per-receiver claims (SPEC §7c), full [N, N] grid — this
        # reference is a test fixture; the production round keeps the
        # grid at [n_byzantine, N].
        supg = (_draw(seed, rng.STREAM_EQUIV, ur, uidx[:, None],
                      uidx[None, :]) & jnp.uint32(1)).astype(bool)
        sendg = (supg & (byz & bcast)[:, None]
                 & (idx[:, None] != idx[None, :]))
        if not no_part:
            sendg &= ~part_active | (side[:, None] == side[None, :])
        extra = jnp.sum(sendg.astype(jnp.int32), axis=0)         # [N]
        extra_sn = jnp.broadcast_to(extra[:, None], (N, S)).T
    else:
        extra_sn = None

    def b32(x):
        return x.astype(jnp.int32)

    bits = (b32(pp_seen) | (b32(prepared) << 1) | (b32(committed) << 2)
            | ((b32(honest) | (b32(bcast) << 1))[:, None] << 3))
    if not no_part:
        bits |= ((b32(side) | (b32(side_ok(0)) << 1)
                  | (b32(side_ok(1)) << 2))[:, None] << 5)
    if crash_on:
        bits |= b32(up)[:, None] << 8
    tal = _SortedTally(pp_val.T, bits.T, extra_sn)
    pp_seen_s, prepared_s, committed_s = tal.bit(0), tal.bit(1), tal.bit(2)
    honest_s, bcast_s = tal.bit(3), tal.bit(4)
    hb_s = honest_s & bcast_s
    extra_s = jnp.int32(0) if tal.extra is None else tal.extra

    def counts_for_s(relevant_s):
        if no_part:
            cnt = tal.count(hb_s & relevant_s)
        else:
            c0 = tal.count(hb_s & tal.bit(6) & relevant_s)
            c1 = tal.count(hb_s & tal.bit(7) & relevant_s)
            cnt = jnp.where(tal.bit(5), c1, c0)
        self_adj = (honest_s & relevant_s & ~bcast_s).astype(jnp.int32)
        return cnt + self_adj + extra_s

    # ---- P4 prepare tally.
    c4 = counts_for_s(pp_seen_s)
    prep_hit_s = pp_seen_s & (c4 >= Q)
    if crash_on:
        prep_hit_s &= tal.bit(8)
    prep_new_s = prep_hit_s & ~prepared_s       # telemetry (DCE'd when off)
    prep_miss_s = pp_seen_s & ~prepared_s & ~prep_hit_s
    prepared2_s = prepared_s | prep_hit_s

    # ---- P5 commit tally.
    c5 = counts_for_s(prepared2_s)
    commit_now_s = prepared2_s & (c5 >= Q) & ~committed_s
    if crash_on:
        commit_now_s &= tal.bit(8)
    commit_miss_s = prepared2_s & ~committed_s & (c5 < Q)  # telemetry

    packed = tal.unsort(b32(prepared2_s) | (b32(commit_now_s) << 1))
    prepared = (packed & 1).astype(bool)
    commit_now = (packed >> 1).astype(bool)
    dval = jnp.where(commit_now, pp_val, dval)
    committed = committed | commit_now

    # ---- P6 decide gossip.
    dec = honest[:, None] & bcast[:, None] & committed            # [N, S]
    if no_part:
        src = jnp.where(dec, idx[:, None], N)
        imin_rows = jnp.min(src, axis=0)[None, :]                 # [1, S]
        imin = jnp.broadcast_to(imin_rows, (N, S))
    else:
        rows = []
        for b in (0, 1):
            src = jnp.where(dec & side_ok(b)[:, None], idx[:, None], N)
            rows.append(jnp.min(src, axis=0))                     # [S]
        imin_rows = jnp.stack(rows)                               # [2, S]
        imin = imin_rows[side]                                    # [N, S]
    adopt = (imin < N) & ~committed
    if crash_on:
        adopt &= up[:, None]
    val_rows = dval[jnp.clip(imin_rows, 0, N - 1),
                    sarange[None, :]]                             # [1|2, S]
    vfull = (jnp.broadcast_to(val_rows, (N, S)) if no_part
             else val_rows[side])
    dval = jnp.where(adopt, vfull, dval)
    committed = committed | adopt

    # ---- P7 timer.
    new_commit = jnp.any(committed & ~committed_at_start, axis=1)
    timer = jnp.where(reset | new_commit, jnp.where(new_commit, 0, timer),
                      timer + 1)

    if crash_on:
        (view, timer, pp_seen, pp_view, pp_val, prepared, committed,
         dval) = freeze_down(
            down, frozen, (view, timer, pp_seen, pp_view, pp_val,
                           prepared, committed, dval))

    new = PbftState(seed, view, timer, pp_seen, pp_view, pp_val,
                    prepared, committed, dval, down)
    if not telem:
        return new
    cnt = lambda m: jnp.sum(m.astype(jnp.int32))  # noqa: E731
    cz = crash_counts(_crashed, rec, down) if crash_on else crash_counts()
    # SPEC §9 tail (zeros — the retired round predates the switch model
    # and is only ever compared against flat-mode runs, where the
    # production counters are identically zero too).
    az = agg_counts()
    # SPEC §7c safety tail — same reductions as the production kernel
    # (engines/pbft_bcast.py). The retired round is flat-only, so the
    # poison axes are structurally off and `equiv` alone gates the math.
    if equiv:
        nw = commit_now & honest[:, None]
        forked = (jnp.any(nw, axis=0)
                  & (jnp.max(jnp.where(nw, pp_val, I32_MIN), axis=0)
                     != jnp.min(jnp.where(nw, pp_val, I32_MAX), axis=0)))
        cm = committed & honest[:, None]
        conflicts = (jnp.any(cm, axis=0)
                     & (jnp.max(jnp.where(cm, dval, I32_MIN), axis=0)
                        != jnp.min(jnp.where(cm, dval, I32_MAX), axis=0)))
        sz = safety_counts(forked, conflicts)
    else:
        sz = safety_counts()
    # SPEC §B desync tail — same reductions as the production kernel
    # (the pacemaker was per-node before AND after the sort diet, so the
    # twin emits LIVE values here, not zeros: view spread and P1
    # catch-ups under drop/crash must match counter-for-counter).
    syncz = sync_counts(view, honest & ~down, catch)
    vec = jnp.stack([cnt(prep_new_s), cnt(prep_miss_s), cnt(commit_now_s),
                     cnt(commit_miss_s), cnt(adopt),
                     jnp.sum(jnp.maximum(view - st.view, 0)), *cz, *az,
                     *sz, *syncz])
    return new, vec


def sorted_tally_round_telem(cfg: Config, st: PbftState, r):
    return sorted_tally_round(cfg, st, r, telem=True)


def reference_engine() -> EngineDef:
    """The retired round behind the production EngineDef seam, so tests
    drive it through the same runner/chunk machinery as the real one."""
    return EngineDef("pbft-bcast-retired", pbft_init, sorted_tally_round,
                     _extract, _pspec, telemetry_names=PBFT_TELEMETRY,
                     round_telem=sorted_tally_round_telem)
