"""SPEC §9 in-network vote aggregation (net_model="switch") + the §A.4
correlated DPoS producer-suppression stream.

Three contracts:

  * **Oracle parity.** Switch-model runs are byte-differential against
    the C++ oracle (cpp/oracle.cpp AggNet) for every vote-counting
    engine — raft (dense + §3b capped), pbft (edge + bcast), paxos,
    hotstuff — including aggregator-failure/stale compositions with
    drop/partition/churn/§6c crash/§A.2 delay/byzantine modes, and
    through the one-program f-ladder (per-rung payloads byte-equal to
    standalone switch runs).
  * **Flat no-op.** net_model="flat" with the new Config fields at
    their defaults is the PRE-SPEC-§9 program: bit-identity per engine
    (old-style config JSON without the fields resolves to the same
    digest) and the committed hlocheck fingerprints stay byte-stable
    modulo the new fields (pinned by the hlocheck gate itself).
  * **No silent ignores.** dpos rejects the switch; agg knobs reject
    flat; suppression rejects non-dpos.
"""
from __future__ import annotations

import dataclasses
import json

import numpy as np
import pytest

from consensus_tpu.core.config import Config
from consensus_tpu.core import serialize
from consensus_tpu.network import simulator

SW = dict(net_model="switch", n_aggregators=3, agg_fail_rate=0.15,
          agg_stale_rate=0.25, agg_max_stale=3)

# Composed base adversary shared by the parity grid.
ADV = dict(drop_rate=0.2, partition_rate=0.1, churn_rate=0.03,
           max_delay_rounds=2, crash_prob=0.08, recover_prob=0.3)

PARITY_CONFIGS = {
    "raft-dense": dict(protocol="raft", n_nodes=9, n_rounds=64, n_sweeps=2,
                       log_capacity=32, max_entries=24, seed=5, **ADV, **SW),
    "raft-dense-byz-equiv": dict(protocol="raft", n_nodes=9, n_rounds=48,
                                 n_sweeps=2, log_capacity=32, max_entries=24,
                                 seed=7, drop_rate=0.15, n_byzantine=2,
                                 byz_mode="equivocate", **SW),
    "raft-dense-byz-silent": dict(protocol="raft", n_nodes=9, n_rounds=48,
                                  n_sweeps=1, log_capacity=32,
                                  max_entries=24, seed=8, drop_rate=0.15,
                                  n_byzantine=2, byz_mode="silent", **SW),
    "raft-capped": dict(protocol="raft", n_nodes=64, max_active=4,
                        n_rounds=64, n_sweeps=2, log_capacity=32,
                        max_entries=24, seed=11, max_crashed=5, **ADV, **SW),
    "raft-capped-byz": dict(protocol="raft", n_nodes=32, max_active=4,
                            n_rounds=48, n_sweeps=2, log_capacity=32,
                            max_entries=24, seed=13, drop_rate=0.15,
                            n_byzantine=5, byz_mode="equivocate", **SW),
    "pbft-edge": dict(protocol="pbft", f=2, n_nodes=7, n_rounds=64,
                      n_sweeps=2, log_capacity=16, seed=3, **ADV, **SW),
    "pbft-edge-byz-equiv": dict(protocol="pbft", f=3, n_nodes=10,
                                n_rounds=48, n_sweeps=2, log_capacity=16,
                                seed=6, drop_rate=0.15, partition_rate=0.1,
                                n_byzantine=2, byz_mode="equivocate", **SW),
    "pbft-bcast": dict(protocol="pbft", fault_model="bcast", f=2, n_nodes=7,
                       n_rounds=64, n_sweeps=2, log_capacity=16, seed=3,
                       **ADV, **SW),
    "pbft-bcast-byz-equiv": dict(protocol="pbft", fault_model="bcast", f=3,
                                 n_nodes=10, n_rounds=48, n_sweeps=2,
                                 log_capacity=16, seed=5, drop_rate=0.15,
                                 partition_rate=0.1, n_byzantine=2,
                                 byz_mode="equivocate", **SW),
    "pbft-bcast-byz-silent": dict(protocol="pbft", fault_model="bcast", f=3,
                                  n_nodes=10, n_rounds=48, n_sweeps=1,
                                  log_capacity=16, seed=9, drop_rate=0.2,
                                  n_byzantine=3, byz_mode="silent", **SW),
    "paxos": dict(protocol="paxos", n_nodes=15, n_rounds=64, n_sweeps=2,
                  log_capacity=24, seed=4, **ADV, **SW),
    "paxos-capped-proposers": dict(protocol="paxos", n_nodes=21,
                                   n_proposers=4, n_rounds=64, n_sweeps=2,
                                   log_capacity=16, seed=6, drop_rate=0.25,
                                   **SW),
    "hotstuff": dict(protocol="hotstuff", f=2, n_nodes=7, n_rounds=64,
                     n_sweeps=2, log_capacity=64, seed=3, n_byzantine=1,
                     **ADV, **SW),
}


def _both(base: dict):
    rt = simulator.run(Config(engine="tpu", **base), warmup=False)
    rc = simulator.run(Config(engine="cpu", **base))
    return rt, rc


@pytest.mark.parametrize("name", sorted(PARITY_CONFIGS))
def test_switch_oracle_parity(name):
    rt, rc = _both(PARITY_CONFIGS[name])
    assert rt.digest == rc.digest, f"{name}: switch run diverged"


def test_switch_oracle_parity_500_nodes():
    # The acceptance bound says N <= 2k; a ~500-node pbft-bcast run
    # exercises real multi-segment geometry (K = 8 over 499 nodes).
    base = dict(protocol="pbft", fault_model="bcast", f=166, n_nodes=499,
                n_rounds=24, n_sweeps=1, log_capacity=8, seed=2,
                drop_rate=0.1, partition_rate=0.05, net_model="switch",
                n_aggregators=8, agg_fail_rate=0.1, agg_stale_rate=0.2,
                agg_max_stale=2)
    rt, rc = _both(base)
    assert rt.digest == rc.digest


def test_switch_k1_and_kn_geometry():
    # K = 1 (one global aggregator) and K = N (one node per segment)
    # are the degenerate segmentations most likely to break the
    # pad/reshape math.
    for k in (1, 9):
        base = dict(protocol="raft", n_nodes=9, n_rounds=48, n_sweeps=1,
                    log_capacity=32, max_entries=24, seed=21,
                    drop_rate=0.2, net_model="switch", n_aggregators=k,
                    agg_fail_rate=0.2, agg_stale_rate=0.3, agg_max_stale=2)
        rt, rc = _both(base)
        assert rt.digest == rc.digest, f"K={k} diverged"


def test_fsweep_switch_rungs_equal_standalone():
    from consensus_tpu.engines.pbft_sweep import (pbft_fsweep_run,
                                                  rung_payloads)
    fs = [1, 2, 3]
    for fm in ("edge", "bcast"):
        base = Config(protocol="pbft", fault_model=fm, f=1, n_nodes=4,
                      n_rounds=48, n_sweeps=2, log_capacity=12, seed=7,
                      drop_rate=0.15, partition_rate=0.1, churn_rate=0.02,
                      max_delay_rounds=2, **SW)
        pls = rung_payloads(pbft_fsweep_run(base, fs))
        for k, f in enumerate(fs):
            solo = dataclasses.replace(base, f=f, n_nodes=3 * f + 1,
                                       seed=base.seed + k)
            rt = simulator.run(solo, warmup=False)
            rc = simulator.run(dataclasses.replace(solo, engine="cpu"))
            assert rt.digest == rc.digest, (fm, f)
            assert serialize.digest(pls[k]) == rt.digest, (fm, f)


def test_fsweep_switch_rejects_oversized_k():
    from consensus_tpu.engines.pbft_sweep import pbft_fsweep_run
    base = Config(protocol="pbft", fault_model="bcast", f=5, n_nodes=16,
                  n_rounds=16, n_sweeps=1, log_capacity=8, seed=1,
                  net_model="switch", n_aggregators=8)
    with pytest.raises(ValueError, match="n_aggregators"):
        pbft_fsweep_run(base, [1, 3])  # rung f=1 has N=4 < K=8


# --- flat is a compiled no-op ---------------------------------------------

FLAT_SMALL = {
    "raft": dict(protocol="raft", n_nodes=7, n_rounds=32, log_capacity=16,
                 max_entries=12, drop_rate=0.1),
    "raft-sparse": dict(protocol="raft", n_nodes=32, max_active=4,
                        n_rounds=32, log_capacity=16, max_entries=12,
                        drop_rate=0.1),
    "pbft": dict(protocol="pbft", f=2, n_nodes=7, n_rounds=32,
                 log_capacity=8, drop_rate=0.1),
    "pbft-bcast": dict(protocol="pbft", fault_model="bcast", f=2, n_nodes=7,
                       n_rounds=32, log_capacity=8, drop_rate=0.1),
    "paxos": dict(protocol="paxos", n_nodes=9, n_rounds=32, log_capacity=8,
                  drop_rate=0.1),
    "dpos": dict(protocol="dpos", n_nodes=24, n_candidates=12,
                 n_producers=4, n_rounds=32, log_capacity=48,
                 drop_rate=0.1),
    "hotstuff": dict(protocol="hotstuff", f=2, n_nodes=7, n_rounds=32,
                     log_capacity=32, drop_rate=0.1),
}


@pytest.mark.parametrize("name", sorted(FLAT_SMALL))
def test_flat_defaults_bit_identical(name):
    """A config built from PRE-§9 JSON (none of the new fields present)
    must resolve to the identical Config — and hence the identical
    compiled program and digest — as one built today with the fields at
    their defaults (the PR 10 compiled-no-op discipline; the compiled
    side is pinned by the hlocheck fingerprints staying byte-stable)."""
    base = FLAT_SMALL[name]
    cfg = Config(engine="tpu", seed=3, n_sweeps=2, **base)
    doc = json.loads(cfg.to_json())
    for field in ("net_model", "n_aggregators", "agg_fail_rate",
                  "agg_stale_rate", "agg_max_stale", "suppress_rate",
                  "suppress_window", "agg_byz", "agg_poison_rate",
                  "byz_uplink_rate"):
        doc.pop(field)
    old_style = Config.from_json(json.dumps(doc))
    assert old_style == cfg
    assert simulator.run(old_style, warmup=False).digest \
        == simulator.run(cfg, warmup=False).digest


def test_config_rejections():
    ok = dict(protocol="raft", n_nodes=5)
    with pytest.raises(ValueError, match="net_model"):
        Config(**ok, net_model="mesh")
    with pytest.raises(ValueError, match="producer row"):
        Config(protocol="dpos", n_nodes=24, n_candidates=12,
               n_producers=4, net_model="switch", n_aggregators=2)
    with pytest.raises(ValueError, match="n_aggregators"):
        Config(**ok, net_model="switch")          # K = 0
    with pytest.raises(ValueError, match="n_aggregators"):
        Config(**ok, net_model="switch", n_aggregators=6)  # K > N
    with pytest.raises(ValueError, match="net_model='switch'"):
        Config(**ok, agg_fail_rate=0.1)           # agg knob without switch
    with pytest.raises(ValueError, match="net_model='switch'"):
        Config(**ok, agg_max_stale=3)
    with pytest.raises(ValueError, match="agg_max_stale"):
        Config(**ok, net_model="switch", n_aggregators=2, agg_max_stale=9)
    with pytest.raises(ValueError, match="suppress_rate"):
        Config(**ok, suppress_rate=0.2)           # non-dpos suppression
    with pytest.raises(ValueError, match="suppress_window"):
        Config(protocol="dpos", n_nodes=24, n_candidates=12,
               n_producers=4, suppress_window=8)  # window without rate


def test_oracle_rejects_invalid_switch():
    from consensus_tpu.oracle import bindings
    cfg = Config(protocol="hotstuff", f=1, n_nodes=4, n_rounds=8,
                 log_capacity=8, engine="cpu", net_model="switch",
                 n_aggregators=2)
    # Doctor an impossible K past the Python validation to prove the
    # native layer rejects it too (no silent divergence).
    bad = dataclasses.replace(cfg)
    object.__setattr__(bad, "n_aggregators", 9)
    with pytest.raises(RuntimeError):
        bindings.hotstuff_run(bad)


# --- telemetry -------------------------------------------------------------

def test_agg_telemetry_counters():
    stats: dict = {}
    cfg = Config(protocol="hotstuff", f=2, n_nodes=7, n_rounds=64,
                 n_sweeps=1, log_capacity=64, seed=11, engine="tpu",
                 net_model="switch", n_aggregators=2, agg_fail_rate=0.4,
                 agg_stale_rate=0.4, agg_max_stale=4)
    r = simulator.run(cfg, warmup=False, stats=stats, telemetry=True)
    tot = r.extras["telemetry"]["totals"]
    assert tot["agg_down_rounds"] > 0
    assert tot["stale_serves"] > 0
    # Flat runs report the counters as zeros (the tail exists, inert).
    r0 = simulator.run(dataclasses.replace(cfg, net_model="flat",
                                           n_aggregators=0,
                                           agg_fail_rate=0.0,
                                           agg_stale_rate=0.0,
                                           agg_max_stale=1),
                       warmup=False, stats={}, telemetry=True)
    tot0 = r0.extras["telemetry"]["totals"]
    assert tot0["agg_down_rounds"] == 0 and tot0["stale_serves"] == 0


# --- SPEC §A.4 correlated producer suppression -----------------------------

SUPPRESS_BASE = dict(protocol="dpos", n_nodes=24, n_rounds=96, n_sweeps=2,
                     log_capacity=96, n_candidates=12, n_producers=3,
                     epoch_len=48, seed=5, drop_rate=0.2, churn_rate=0.02,
                     miss_rate=0.1, max_delay_rounds=2, crash_prob=0.05,
                     recover_prob=0.3, suppress_rate=0.3,
                     suppress_window=24)


def test_suppress_oracle_parity():
    rt, rc = _both(SUPPRESS_BASE)
    assert rt.digest == rc.digest


def test_suppress_window_correlation():
    """The §A.4 point: inside one window a producer's fate is ONE draw,
    so a suppressed producer misses EVERY slot it is scheduled for in
    the window — verified against the chain: no block from a
    window-suppressed producer may appear in that window's rounds."""
    from consensus_tpu.core import rng as crng
    base = dict(SUPPRESS_BASE, drop_rate=0.0, churn_rate=0.0,
                miss_rate=0.0, crash_prob=0.0, recover_prob=0.0,
                max_delay_rounds=0, suppress_rate=0.5, n_sweeps=1)
    cfg = Config(engine="tpu", **base)
    out = simulator.run(cfg, warmup=False)
    cut = cfg.suppress_cutoff
    W = cfg.suppress_window
    chain_r, chain_p = out.rec_a[0, 0], out.rec_b[0, 0]  # validator 0
    n = int(out.counts[0, 0])
    suppressed_blocks = [
        (int(r), int(p)) for r, p in zip(chain_r[:n], chain_p[:n])
        if int(crng.random_u32_np(cfg.seed, crng.STREAM_SUPPRESS,
                                  int(r) // W, 0, int(p))) < cut]
    assert suppressed_blocks == []


def test_suppress_stalls_lib_below_iid_floor():
    """RESILIENCE.md §8's negative result: iid slot-miss keying keeps
    lib_ratio >= ~0.8. The correlated stream must do what iid cannot —
    at a window spanning the epoch, a suppressed producer vanishes
    from the suffix wholesale and LIB stalls well below that floor."""
    base = dict(SUPPRESS_BASE, n_sweeps=4, suppress_rate=0.45,
                suppress_window=48, miss_rate=0.0, crash_prob=0.0,
                recover_prob=0.0, drop_rate=0.05, churn_rate=0.0,
                max_delay_rounds=0)
    r = simulator.run(Config(engine="tpu", **base), warmup=False)
    lib = np.asarray(r.extras["lib"], dtype=np.int64)
    head = np.asarray(r.counts, dtype=np.int64)
    ratio = float((lib + 1).mean() / max(1.0, float(head.mean())))
    assert ratio < 0.7, f"correlated suppression should stall LIB, got {ratio}"


def test_knob_batch_rejects_gated_off_suppress_lane():
    """run_knob_batch's gate-representativeness guard must cover the
    new suppress_cutoff KNOB column: a base with suppression OFF leaves
    the draw untraced, so a lane varying that column would be silently
    ignored — the guard has to refuse it."""
    import numpy as np

    from consensus_tpu.core.knobs import KNOB_COLUMNS
    from consensus_tpu.network import runner
    from consensus_tpu.network.simulator import engine_def
    cfg = Config(protocol="dpos", n_nodes=24, n_rounds=16, n_sweeps=1,
                 log_capacity=32, n_candidates=12, n_producers=3,
                 epoch_len=8, seed=1, drop_rate=0.2, telemetry_window=4)
    assert not cfg.suppress_on
    kmat = np.array([[getattr(cfg, c) for c in KNOB_COLUMNS]], np.uint32)
    kmat[0, KNOB_COLUMNS.index("suppress_cutoff")] = 12345
    with pytest.raises(ValueError, match="gates that adversary OFF"):
        runner.run_knob_batch(cfg, engine_def(cfg),
                              np.array([cfg.seed], np.uint32), kmat)


# --- scenario --------------------------------------------------------------

def test_stale_aggregator_scenario_passes_at_tuned_shape():
    from consensus_tpu import scenarios
    sc = scenarios.get("stale-aggregator-inconsistency")
    cfg = Config(protocol="hotstuff", engine="tpu", n_sweeps=2, seed=11,
                 **sc.tuned)
    applied = scenarios.apply(cfg, sc)
    assert applied.net_model == "switch"
    r = simulator.run(applied, warmup=False, stats={}, telemetry=True)
    verdict = scenarios.evaluate(sc, r)
    assert verdict["passed"], verdict["checks"]
