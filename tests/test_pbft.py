"""PBFT: differential byte-equivalence + agreement invariant (SPEC §6)."""
import dataclasses

import numpy as np
import pytest

from consensus_tpu import Config
from consensus_tpu.network import simulator

from helpers import run_cached


def _cfg(f=1, **kw):
    base = dict(protocol="pbft", n_nodes=3 * f + 1, f=f, n_rounds=64,
                log_capacity=16, n_sweeps=4, seed=777)
    base.update(kw)
    return Config(**base)


CFGS = [
    _cfg(),
    _cfg(n_byzantine=1, seed=1),
    _cfg(f=2, n_byzantine=2, drop_rate=0.2, seed=2),
    _cfg(partition_rate=0.3, seed=3),
    _cfg(n_byzantine=1, drop_rate=0.25, churn_rate=0.05, seed=4),
    _cfg(f=3, n_byzantine=3, drop_rate=0.3, partition_rate=0.2,
         churn_rate=0.1, n_rounds=96, seed=5),
    # Equivocating byzantine adversary (SPEC §6 byz_mode="equivocate"):
    # conflicting pre-prepares + per-receiver split votes at n_byzantine=f.
    _cfg(n_byzantine=1, byz_mode="equivocate", seed=6),
    _cfg(f=2, n_byzantine=2, byz_mode="equivocate", drop_rate=0.2, seed=7),
    _cfg(f=3, n_byzantine=3, byz_mode="equivocate", drop_rate=0.25,
         partition_rate=0.15, churn_rate=0.1, n_rounds=96, seed=8),
    # Equivocation up the ladder (VERDICT r3 #5): a full f of attackers
    # at f=8 (N=25) — the 2f+1 tallies' value-independent byz votes
    # (pbft.py P4/P5 `extra`) are exercised well beyond toy sizes.
    _cfg(f=8, n_byzantine=8, byz_mode="equivocate", drop_rate=0.2,
         churn_rate=0.05, view_timeout=4, n_rounds=48, n_sweeps=2, seed=9),
    # SPEC §B per-node timer skew: premature view changes fire at round
    # start (P2's timeout precedes pre-prepare), composed with drops so
    # the f+1 catch-up rule has real spread to heal.
    _cfg(f=2, desync_rate=0.2, max_skew_rounds=4, view_timeout=4,
         drop_rate=0.15, seed=10),
    _cfg(f=3, n_byzantine=3, byz_mode="equivocate", desync_rate=0.15,
         max_skew_rounds=3, view_timeout=4, drop_rate=0.2,
         partition_rate=0.1, n_rounds=96, seed=12),
]


@pytest.mark.parametrize("cfg", CFGS)
def test_pbft_decided_log_byte_equivalence(cfg):
    tpu = run_cached(cfg)
    cpu = run_cached(dataclasses.replace(cfg, engine="cpu"))
    assert tpu.payload == cpu.payload, (tpu.digest, cpu.digest)


@pytest.mark.parametrize("cfg", CFGS)
def test_pbft_agreement_per_slot(cfg):
    """Safety: all nodes that commit a slot commit the same value, despite
    up to f silent-faulty nodes and network faults."""
    from consensus_tpu.engines.pbft import pbft_run
    out = pbft_run(cfg)
    comm, dv = out["committed"], out["dval"]
    for b in range(cfg.n_sweeps):
        for s in range(cfg.log_capacity):
            c = comm[b, :, s]
            if c.any():
                vals = np.unique(dv[b, c, s])
                assert vals.size == 1, f"sweep {b} slot {s}: {vals}"


def test_pbft_equivocators_actually_attack():
    """The equivocate adversary must be observable — byzantine primaries
    hand out conflicting pre-prepares, so honest nodes' accepted pp_val
    must differ across receivers for some (view, slot) — while agreement
    on COMMITTED values still holds (checked by test_pbft_agreement_per_slot
    over the equivocate configs above)."""
    from consensus_tpu.engines.pbft import pbft_run
    # Churn rotates views so the byz node (primary when view ≡ 3 mod 4)
    # actually gets the primary slot; drops make its split votes marginal.
    cfg = _cfg(n_byzantine=1, byz_mode="equivocate", n_rounds=64,
               view_timeout=2, churn_rate=0.3, drop_rate=0.2, seed=11)
    out = pbft_run(cfg)
    silent = pbft_run(dataclasses.replace(cfg, byz_mode="silent"))
    # The attack must change observable behavior vs a silent byz node.
    assert not (np.asarray(out["committed"]) == np.asarray(silent["committed"])).all() \
        or not (np.asarray(out["pp_val"]) == np.asarray(silent["pp_val"])).all()


def test_pbft_progress_with_f_silent_nodes():
    """Liveness sanity: with exactly f silent nodes and a clean network,
    every slot still commits (quorums of 2f+1 out of the 2f+1 honest)."""
    cfg = _cfg(f=2, n_byzantine=2, n_rounds=64)
    res = run_cached(cfg)
    honest = cfg.n_nodes - cfg.n_byzantine
    assert (res.counts[:, :honest] == cfg.log_capacity).all()
