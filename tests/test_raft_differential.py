"""Differential tests: TPU-engine Raft vs C++ oracle, byte-equal decided logs.

This is the framework's acceptance criterion (BASELINE.json:2,5;
SURVEY.md §4.3): both engines run identical (config, seed) and must produce
identical canonical serializations — compared on raw bytes, reported as
SHA-256 digests.
"""
import dataclasses

import numpy as np
import pytest

from consensus_tpu import Config
from consensus_tpu.network import simulator

from helpers import run_cached

CLEAN = Config(protocol="raft", n_nodes=5, n_rounds=64, log_capacity=128,
               max_entries=100, n_sweeps=2, seed=7)
ADVERSARIAL = [
    dataclasses.replace(CLEAN, drop_rate=0.25, seed=11, n_sweeps=4),
    dataclasses.replace(CLEAN, partition_rate=0.3, seed=12, n_sweeps=4),
    dataclasses.replace(CLEAN, churn_rate=0.1, seed=13, n_sweeps=4),
    dataclasses.replace(CLEAN, n_nodes=9, drop_rate=0.3, partition_rate=0.2,
                        churn_rate=0.05, n_rounds=128, seed=14, n_sweeps=4),
    # Storage-dtype tiers of the match/next arrays (engines/raft.py
    # _match_dtype): u8 is covered by every config above; these pin the
    # u8 saturation boundary (L=254 ⇒ next_idx reaches exactly 255),
    # the u16 tier, and the i32 tier.
    dataclasses.replace(CLEAN, log_capacity=254, max_entries=254,
                        n_rounds=300, n_sweeps=1, seed=16),
    dataclasses.replace(CLEAN, log_capacity=300, max_entries=260,
                        n_rounds=96, drop_rate=0.2, seed=15, n_sweeps=2),
    dataclasses.replace(CLEAN, log_capacity=65600, max_entries=32,
                        n_rounds=24, n_sweeps=1, seed=17),
    # N=96 > 64 puts _pick_row's [N, N] masks above the _SMALL_PICK
    # gate: the one-hot-reduce path (the one every benchmark shape
    # takes) gets oracle-differential coverage, not just the small-N
    # gather path.
    dataclasses.replace(CLEAN, n_nodes=96, n_rounds=96, log_capacity=64,
                        max_entries=48, drop_rate=0.2, churn_rate=0.05,
                        seed=18, n_sweeps=2),
]


@pytest.mark.parametrize("cfg", [CLEAN] + ADVERSARIAL)
def test_raft_decided_log_byte_equivalence(cfg):
    tpu = run_cached(dataclasses.replace(cfg, engine="tpu"))
    cpu = run_cached(dataclasses.replace(cfg, engine="cpu"))
    assert tpu.digest == cpu.digest
    assert tpu.payload == cpu.payload


def test_raft_makes_progress_clean():
    res = run_cached(dataclasses.replace(CLEAN, engine="tpu"))
    # A clean 64-round run must elect a leader and commit a healthy log.
    assert res.counts.max() >= 40


def test_raft_rerun_bitwise_deterministic():
    a = run_cached(dataclasses.replace(CLEAN, engine="tpu"))
    b = run_cached(dataclasses.replace(CLEAN, engine="tpu"))
    assert a.payload == b.payload
