"""Observatory layer 2: the cross-run perf ledger (tools/ledger.py).

Stdlib-fast (no jax): the ledger folds committed history — driver
BENCH captures, multichip dry runs, benchmarks/RESULTS.json — plus the
cost cards into benchmarks/LEDGER.json. Pins: every measured RESULTS
row carries a measured-vs-predicted ratio, stale_timing markers
propagate into rows (not just a startup stderr line), instrument
classes never cross-compare, the noise-banded verdict fires on real
regressions only, and the schema tripwire rejects drift.
"""
import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from tools import ledger, validate_trace  # noqa: E402

REPO = pathlib.Path(__file__).resolve().parents[1]


def _doc():
    return ledger.build(REPO)


def test_every_results_tpu_row_has_measured_vs_predicted():
    doc = _doc()
    tpu = [r for r in doc["rows"] if r["kind"] == "results-tpu"]
    assert tpu, "RESULTS.json produced no measured rows"
    for r in tpu:
        assert (r["predicted_steps_per_sec"] or 0) > 0, r["name"]
        assert (r["measured_vs_predicted"] or 0) > 0, r["name"]
    # The padded f-ladder row is costed by the fsweep card (CARD_FOR).
    assert any(r["name"] == "pbft-fsweep-one-program" for r in tpu)


def test_oracle_rows_form_their_own_series():
    doc = _doc()
    oracle = [r for r in doc["rows"] if r["kind"] == "results-oracle"]
    assert oracle
    for r in oracle:
        assert r["predicted_steps_per_sec"] is None  # no device roofline
        assert r["platform"] == "cpu-oracle"
    # A single-core baseline must never read as a TPU regression:
    # raft-5node has exactly one tpu measurement (RESULTS) — an oracle
    # row leaking into the class would make it a 2-point series whose
    # 0.69x 'latest' reds the build.
    assert doc["series"]["raft-5node@tpu"]["n_points"] == 1
    oracle_sps = {r["steps_per_sec"] for r in oracle}
    for key, s in doc["series"].items():
        if key.endswith("@tpu"):
            assert not oracle_sps & {p["steps_per_sec"]
                                     for p in s["points"]}, key
    assert "raft-100k@oracle" in doc["series"]
    assert doc["series"]["raft-100k@tpu"]["n_points"] >= 2  # bench + RESULTS


def test_stale_timing_markers_propagate_into_rows():
    doc = _doc()
    stale = [r for r in doc["rows"] if r["stale"]]
    assert [r["name"] for r in stale] == ["pbft-100k-bcast"]
    assert "sort-diet" in stale[0]["stale"]
    assert doc["stale_rows"] and doc["stale_rows"][0]["name"] == \
        "pbft-100k-bcast"


def test_committed_history_has_no_regressions():
    doc = _doc()
    assert doc["regressions"] == [], doc["regressions"]
    # The known-stale pbft row reads stale-latest, never regression.
    verd = doc["series"]["pbft-100k-bcast@tpu"]["verdict"]
    assert verd in ("stale-latest", "new")


def test_series_verdicts_synthetic():
    def row(name, sps, plat="tpu", stale=None, seq=1, ok=True):
        return ledger._row(source="s", kind="driver-bench", name=name,
                           seq=seq, platform=plat, steps_per_sec=sps,
                           stale=stale, ok=ok)

    s = ledger.build_series([row("a", 100e6), row("a", 100e6 * 0.9,
                                                  seq=2)])
    assert s["a@tpu"]["verdict"] == "ok"  # within the ±15% band
    s = ledger.build_series([row("a", 100e6), row("a", 100e6 * 0.7,
                                                  seq=2)])
    assert s["a@tpu"]["verdict"] == "regression"
    s = ledger.build_series([row("a", 100e6),
                             row("a", 60e6, stale="pre-fix row", seq=2)])
    assert s["a@tpu"]["verdict"] == "stale-latest"
    # ...and a stale point never becomes the BASELINE either: a pre-fix
    # timing that overstated steps/s must not verdict the first fresh
    # correct measurement a regression.
    s = ledger.build_series([row("a", 100e6, stale="pre-fix row"),
                             row("a", 10e6, seq=2)])
    assert s["a@tpu"]["verdict"] == "new"
    s = ledger.build_series([row("a", 100e6, stale="pre-fix row"),
                             row("a", 10e6, seq=2),
                             row("a", 9.5e6, seq=3)])
    assert s["a@tpu"]["verdict"] == "ok" and s["a@tpu"]["best_prior"] == 10e6
    s = ledger.build_series([row("a", 100e6)])
    assert s["a@tpu"]["verdict"] == "new"
    # ok=false rows (failed/degenerate runs) never drive a verdict —
    # neither as a bogus 'latest' nor as an inflated 'best prior'.
    s = ledger.build_series([row("a", 100e6),
                             row("a", 1e6, seq=2, ok=False)])
    assert s["a@tpu"]["verdict"] == "new"
    s = ledger.build_series([row("a", 500e6, ok=False),
                             row("a", 100e6, seq=2),
                             row("a", 98e6, seq=3)])
    assert s["a@tpu"]["verdict"] == "ok"
    # Chronology beats concatenation order: a FRESH driver capture
    # (timestamped after the RESULTS artifact) must be the series'
    # latest point even though results rows enter the row list last —
    # a 2.8x regression in the newest capture has to fire.
    results_row = ledger._row(source="benchmarks/RESULTS.json",
                              kind="results-tpu", name="a",
                              timestamp=1_785_000_000.0, platform="tpu",
                              steps_per_sec=100e6, ok=True)
    fresh = row("a", 36e6, seq=6)
    fresh["timestamp"] = 1_786_000_000.0
    s = ledger.build_series([row("a", 90e6, seq=5), fresh, results_row])
    assert s["a@tpu"]["verdict"] == "regression"
    assert s["a@tpu"]["latest"] == 36e6
    assert s["a@tpu"]["best_prior"] == 100e6
    # Platform classes never cross-compare.
    s = ledger.build_series([row("a", 100e6), row("a", 1e6, plat="cpu",
                                                  seq=2)])
    assert set(s) == {"a@tpu", "a@cpu"}
    assert all(v["verdict"] == "new" for v in s.values())


def test_bench_trajectory_block_ingested_directly(tmp_path):
    (tmp_path / "benchmarks").mkdir()
    (tmp_path / "BENCH_r09.json").write_text(json.dumps({
        "n": 9, "cmd": "python bench.py", "rc": 0,
        "tail": "irrelevant free text",
        "parsed": {"metric": "raft-100000node-64round-cap8 "
                             "node-round-steps/sec [tpu]",
                   "value": 58.0e6, "unit": "steps/sec",
                   "vs_baseline": 5.8,
                   "trajectory": {"schema": 1, "timestamp": 1785e6,
                                  "platform": "tpu", "protocol": "raft",
                                  "nodes": 100_000, "rounds": 64,
                                  "sweeps": 8, "max_active": 8,
                                  "steps": 51_200_000, "wall_s": 0.883,
                                  "repeats": 3, "max_committed": 61}}}))
    doc = ledger.build(tmp_path)
    [row] = doc["rows"]
    assert row["name"] == "raft-100k"  # flagship shape, from the block
    assert row["wall_s"] == 0.883 and row["steps"] == 51_200_000
    assert row["timestamp"] == 1785e6 and row["ok"] is True


def test_failed_driver_round_keeps_its_hole_visible(tmp_path):
    (tmp_path / "benchmarks").mkdir()
    (tmp_path / "BENCH_r01.json").write_text(json.dumps(
        {"n": 1, "cmd": "python bench.py", "rc": 1,
         "tail": "Traceback ...", "parsed": None}))
    doc = ledger.build(tmp_path)
    [row] = doc["rows"]
    assert row["ok"] is False and "no parseable" in row["notes"]


def test_trajectoryless_round_carries_explicit_marker(tmp_path):
    """A BENCH round with a parseable metric but no trajectory block
    (pre-trajectory capture, or bench.py died before emitting it) is
    marked `no-trajectory` — distinguishable from a healthy thin row."""
    (tmp_path / "benchmarks").mkdir()
    (tmp_path / "BENCH_r02.json").write_text(json.dumps({
        "n": 2, "cmd": "python bench.py", "rc": 0,
        "tail": "raft-100000node-64round-cap8 ...",
        "parsed": {"metric": "raft-100000node-64round-cap8 "
                             "node-round-steps/sec [tpu]",
                   "value": 58.0e6, "unit": "steps/sec"}}))
    doc = ledger.build(tmp_path)
    [row] = doc["rows"]
    assert row["ok"] is True
    assert "no-trajectory" in row["notes"]
    # ...and a round WITH the block stays unmarked.
    from tools.ledger import bench_rows
    assert all("no-trajectory" not in (r["notes"] or "")
               for r in bench_rows(REPO, {}) if r["wall_s"] is not None)


def test_committed_ledger_is_valid_and_regenerable(tmp_path):
    committed = REPO / "benchmarks" / "LEDGER.json"
    errs = validate_trace.validate_ledger(committed)
    assert not errs, errs
    out = tmp_path / "LEDGER.json"
    assert ledger.main(["--repo", str(REPO), "--out", str(out),
                        "--check", "--quiet"]) == 0
    assert not validate_trace.validate_ledger(out)
    # Drift gate, like the cost cards/fingerprints: the build is a pure
    # function of its inputs (no wall clock), so the committed artifact
    # must equal a fresh regeneration — a new BENCH round or RESULTS
    # edit without `make ledger` fails here, not in a reader's hands.
    assert json.loads(out.read_text()) == json.loads(
        committed.read_text()), \
        "committed benchmarks/LEDGER.json is stale — run `make ledger`"


def test_validator_flags_ledger_drift(tmp_path):
    doc = ledger.build(REPO)
    doc["rows"][0]["surprise"] = 1
    for r in doc["rows"]:
        if r["kind"] == "results-tpu":
            r["measured_vs_predicted"] = None
            break
    p = tmp_path / "bad_ledger.json"
    p.write_text(json.dumps(doc))
    errs = validate_trace.validate_ledger(p)
    assert any("surprise" in e for e in errs)
    assert any("measured_vs_predicted" in e for e in errs)
