"""Edge-wise vs dense oracle delivery — byte-identical digests.

The oracle's ``Net`` (cpp/oracle.cpp) answers SPEC §2 delivery queries in
one of two execution strategies: DENSE materializes the [N, N] matrix
once per round (the historic design), EDGE evaluates the counter-based
draw per live edge on demand — O(A·N) per capped round, which is what
makes 100k-node configs oracle-tractable (docs/PERF.md "oracle
asymptotics"). Both evaluate the same pure function of (seed, r, i, j),
so forcing either strategy must not move a single byte of any decided
log. These tests pin that per engine at N ≤ 2k (where dense is still
cheap); the ≥50k-node pairing against the TPU engine lives in
tests/test_oracle_benchscale.py, and cpp/oracle_selftest.cpp
(``run_match``) repeats the check under ASan+UBSan.

For pbft-bcast the knob switches MORE than the Net: auto/edge run the
per-(slot, side) aggregate §6b round, dense the direct per-receiver
definition — so digest equality here cross-checks two independent
derivations of SPEC §6b, not just two delivery-query paths.
"""
import pytest

from consensus_tpu.core.config import Config
from consensus_tpu.network import simulator

ADV = dict(drop_rate=0.08, partition_rate=0.15, churn_rate=0.05)

CONFIGS = {
    # Dense SPEC §3 raft: every pair queried — edge mode recomputes draws.
    "raft-dense": Config(protocol="raft", engine="cpu", n_nodes=96,
                         n_rounds=48, log_capacity=32, max_entries=24,
                         seed=11, **ADV),
    # SPEC §3b capped raft at the old oracle ceiling (auto → edge-wise).
    "raft-capped": Config(protocol="raft", engine="cpu", n_nodes=2048,
                          n_rounds=24, log_capacity=32, max_entries=24,
                          max_active=8, seed=12, **ADV),
    # §3c byzantine tallies query (j, c) back-edges too.
    "raft-capped-byz": Config(protocol="raft", engine="cpu", n_nodes=512,
                              n_rounds=32, log_capacity=32, max_entries=24,
                              max_active=6, n_byzantine=64,
                              byz_mode="equivocate", seed=13, **ADV),
    # Dense SPEC §6 pbft (edge fault model) with equivocation.
    "pbft-edge": Config(protocol="pbft", engine="cpu", f=10, n_nodes=31,
                        n_rounds=24, log_capacity=8, n_byzantine=3,
                        byz_mode="equivocate", seed=14, **ADV),
    # SPEC §6b: aggregate round (auto/edge) vs direct definition (dense).
    "pbft-bcast": Config(protocol="pbft", engine="cpu", fault_model="bcast",
                         f=167, n_nodes=502, n_rounds=24, log_capacity=8,
                         n_byzantine=41, byz_mode="equivocate", seed=15,
                         **ADV),
    # All-propose paxos (P == N: auto stays dense; edge is forced here).
    "paxos": Config(protocol="paxos", engine="cpu", n_nodes=600, n_rounds=12,
                    log_capacity=64, seed=16, **ADV),
    # Capped proposers (7·P < N: auto goes edge-wise; dense is forced).
    "paxos-capped": Config(protocol="paxos", engine="cpu", n_nodes=2000,
                           n_rounds=12, log_capacity=64, n_proposers=5,
                           seed=17, **ADV),
}


@pytest.mark.parametrize("name", sorted(CONFIGS))
def test_dense_edge_and_auto_delivery_digests_identical(name):
    cfg = CONFIGS[name]
    dense = simulator.run(cfg, oracle_delivery="dense")
    edge = simulator.run(cfg, oracle_delivery="edge")
    auto = simulator.run(cfg)  # the per-engine default choice
    assert dense.digest == edge.digest == auto.digest, name
    assert dense.payload == edge.payload


def test_tpu_engine_rejects_delivery_knob():
    cfg = Config(protocol="raft", engine="tpu", n_nodes=5, n_rounds=4)
    with pytest.raises(ValueError, match="oracle_delivery"):
        simulator.run(cfg, warmup=False, oracle_delivery="edge")


def test_dpos_rejects_delivery_knob():
    # DPoS's oracle has no [N, N] delivery layer (one producer row per
    # round is already edge-wise) — the knob would be a silent no-op.
    cfg = Config(protocol="dpos", engine="cpu", n_nodes=32, n_rounds=16,
                 log_capacity=16, n_candidates=8, n_producers=3)
    with pytest.raises(ValueError, match="dpos"):
        simulator.run(cfg, oracle_delivery="edge")


def test_unknown_delivery_rejected():
    cfg = Config(protocol="raft", engine="cpu", n_nodes=8, n_rounds=4)
    with pytest.raises(ValueError, match="unknown oracle delivery"):
        simulator.run(cfg, oracle_delivery="sparse")
