"""Fault-tolerant execution: verified checkpoints, supervised
retry/resume, and the crash-injection harness (docs/RESILIENCE.md).

The framework's acceptance story is bit-identical decided-log digests
across engines AND across interrupted/resumed runs. These tests attack
that story the way real failures would — SIGKILL mid-run, torn/corrupt
snapshot bytes, transient device errors — and assert recovery is
byte-exact every time.

Tier-1 tests are in-process and fast; the subprocess crash tests (a real
``python -m consensus_tpu`` killed by the fault harness) are marked
``slow`` and run in the slow tier (`-m slow`).
"""
import dataclasses
import json
import os
import pathlib
import signal
import subprocess
import sys

import jax.numpy as jnp
import numpy as np
import pytest

from consensus_tpu.core.config import Config
from consensus_tpu.engines import raft
from consensus_tpu.network import faults, runner, simulator, supervisor

CFG = Config(protocol="raft", n_nodes=5, n_rounds=48, n_sweeps=2,
             log_capacity=16, max_entries=8, scan_chunk=8,
             drop_rate=0.1, churn_rate=0.05)
# The same run under the SPEC §6c crash-recover adversary: the
# execution-layer fault model (kills, retries, torn snapshots) must
# compose with the protocol-layer one (simulated node crashes).
CRASH_CFG = dataclasses.replace(CFG, crash_prob=0.15, recover_prob=0.3)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


def _save_rotations(path, cfg, rounds, keep=3):
    """Advance a fresh carry chunk by chunk, saving a rotation at each
    round in ``rounds`` (ascending); returns the engine used."""
    eng = raft.get_engine()
    seeds = jnp.asarray(runner.make_seeds(cfg))
    carry = runner._init_jit(cfg, eng, seeds)
    r = 0
    for target in rounds:
        carry = runner._chunk_jit(cfg, eng, target - r, carry, jnp.int32(r))
        r = target
        runner.save_checkpoint(path, cfg, carry, r, keep=keep)
    return eng


def _digest(out) -> bytes:
    return simulator.decided_payload(CFG, out)[3]


# --- checkpoint integrity + rotation (tier-1) --------------------------------

def test_save_rotates_last_k(tmp_path):
    ck = tmp_path / "ck.npz"
    _save_rotations(ck, CFG, [8, 16, 24, 32], keep=3)
    assert [p.name for p in runner.checkpoint_candidates(ck)] == \
        ["ck.npz", "ck.1.npz", "ck.2.npz"]
    rounds = [runner._read_verified(p)[0]["next_round"]
              for p in runner.checkpoint_candidates(ck)]
    assert rounds == [32, 24, 16]  # newest first; round-8 rotated away


@pytest.mark.parametrize("mode", ["truncate", "flip", "leaf-tamper"])
def test_corrupt_latest_falls_back_to_previous_rotation(tmp_path, mode):
    """The acceptance-criteria corruption half, in-process: a damaged
    latest snapshot is detected via checksum and recovery falls back to
    the previous rotation — and the resumed digest is bit-identical."""
    ck = tmp_path / "ck.npz"
    eng = _save_rotations(ck, CFG, [8, 16], keep=2)
    base = runner.run(CFG, eng)

    faults.corrupt_checkpoint(ck, mode)
    loaded = runner.load_checkpoint(ck, CFG, eng)
    assert loaded is not None and loaded[1] == 8  # fell back to ck.1
    assert runner.peek_checkpoint(ck, CFG) == 8

    resumed = runner.run(CFG, eng, checkpoint_path=ck, resume=True)
    for k in base:
        np.testing.assert_array_equal(base[k], resumed[k], err_msg=k)


def test_kill_between_rotate_and_rename_leaves_fallback_reachable(tmp_path):
    """save_checkpoint's crash window: a kill AFTER ckpt.npz rotated to
    ckpt.1.npz but BEFORE the tmp file renamed into place leaves no
    index-0 file. The candidate scan must step over that hole and find
    the (fully valid) ckpt.1.npz — this is precisely the torn-write
    scenario rotation exists for."""
    ck = tmp_path / "ck.npz"
    eng = _save_rotations(ck, CFG, [8, 16], keep=2)
    # Simulate the mid-rotation kill: newest rotated away, no new ck.npz
    # (and the abandoned tmp file still lying around).
    ck.replace(runner.rotation_path(ck, 1))
    (tmp_path / "ck.tmp.npz").write_bytes(b"torn partial write")
    assert [p.name for p in runner.checkpoint_candidates(ck)] == ["ck.1.npz"]
    loaded = runner.load_checkpoint(ck, CFG, eng)
    assert loaded is not None and loaded[1] == 16
    # A hole mid-ladder (kill one rename earlier) is also stepped over.
    _save_rotations(ck, CFG, [8, 16, 24], keep=3)
    runner.rotation_path(ck, 1).unlink()
    assert [p.name for p in runner.checkpoint_candidates(ck)] == \
        ["ck.npz", "ck.2.npz"]
    faults.corrupt_checkpoint(ck, "truncate")
    assert runner.peek_checkpoint(ck, CFG) == 8  # via ck.2, over the hole


def test_all_rotations_corrupt_restarts_fresh(tmp_path):
    ck = tmp_path / "ck.npz"
    eng = _save_rotations(ck, CFG, [8, 16], keep=2)
    faults.corrupt_checkpoint(ck, "truncate")
    faults.corrupt_checkpoint(runner.rotation_path(ck, 1), "flip")
    assert runner.load_checkpoint(ck, CFG, eng) is None
    assert runner.peek_checkpoint(ck, CFG) is None
    base = runner.run(CFG, eng)
    resumed = runner.run(CFG, eng, checkpoint_path=ck, resume=True)
    for k in base:
        np.testing.assert_array_equal(base[k], resumed[k], err_msg=k)


def test_manifest_tamper_detected(tmp_path):
    """Editing meta (here: next_round) without recomputing the manifest
    CRC must invalidate the snapshot — a resume from a mislabeled round
    would be silently wrong, the worst failure mode this layer has."""
    ck = tmp_path / "ck.npz"
    eng = _save_rotations(ck, CFG, [8], keep=1)
    with np.load(ck) as z:
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
        meta = json.loads(bytes(z["__meta__"]).decode())
    meta["next_round"] = 16  # lie; leaf bytes + CRCs untouched
    np.savez(ck, __meta__=np.frombuffer(json.dumps(meta).encode(),
                                        dtype=np.uint8), **arrays)
    with pytest.raises(runner.CheckpointError, match="manifest"):
        runner._read_verified(ck)
    assert runner.load_checkpoint(ck, CFG, eng) is None


def test_legacy_snapshot_without_integrity_still_loads(tmp_path):
    ck = tmp_path / "ck.npz"
    eng = _save_rotations(ck, CFG, [8], keep=1)
    with np.load(ck) as z:
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
        meta = json.loads(bytes(z["__meta__"]).decode())
    meta.pop("integrity")
    np.savez(ck, __meta__=np.frombuffer(json.dumps(meta).encode(),
                                        dtype=np.uint8), **arrays)
    loaded = runner.load_checkpoint(ck, CFG, eng)
    assert loaded is not None and loaded[1] == 8


def test_rotation_scan_skips_mismatched_configs(tmp_path):
    """Rotations are matched per-candidate: when two runs share a path,
    each config resumes from ITS newest snapshot, not the other's."""
    ck = tmp_path / "ck.npz"
    cfg_b = dataclasses.replace(CFG, seed=CFG.seed + 1)
    eng = _save_rotations(ck, CFG, [8], keep=2)     # cfg A -> ck.npz
    _save_rotations(ck, cfg_b, [16], keep=2)        # cfg B -> ck.npz, A -> .1
    assert runner.load_checkpoint(ck, cfg_b, eng)[1] == 16
    assert runner.load_checkpoint(ck, CFG, eng)[1] == 8   # from ck.1.npz
    assert runner.peek_checkpoint(ck, CFG) == 8


def test_runner_run_keeps_k_checkpoints(tmp_path):
    ck = tmp_path / "ck.npz"
    eng = raft.get_engine()
    runner.run(CFG, eng, checkpoint_path=ck, keep_checkpoints=3)
    # 48 rounds / chunk 8 -> snapshots at 8..40; last 3 retained.
    rounds = [runner._read_verified(p)[0]["next_round"]
              for p in runner.checkpoint_candidates(ck)]
    assert rounds == [40, 32, 24]


# --- fsync durability (tier-1) ----------------------------------------------

def test_fsync_checkpoints_flag_roundtrips(tmp_path, monkeypatch):
    """--fsync-checkpoints: the synced snapshot loads back verbatim,
    os.fsync actually ran (file + directory), and the default path
    issues NO fsync at all (unchanged behavior)."""
    synced = []
    real_fsync = os.fsync
    monkeypatch.setattr(runner.os, "fsync",
                        lambda fd: (synced.append(fd), real_fsync(fd))[1])
    ck = tmp_path / "ck.npz"
    eng = raft.get_engine()
    seeds = jnp.asarray(runner.make_seeds(CFG))
    carry = runner._chunk_jit(CFG, eng, 8, runner._init_jit(CFG, eng, seeds),
                              jnp.int32(0))
    runner.save_checkpoint(ck, CFG, carry, 8)          # default: no fsync
    assert synced == []
    runner.save_checkpoint(ck, CFG, carry, 8, fsync=True)
    assert len(synced) == 2                            # tmp file + directory
    assert runner.load_checkpoint(ck, CFG, eng)[1] == 8

    base = runner.run(CFG, eng)
    ck2 = tmp_path / "ck2.npz"
    out = runner.run(CFG, eng, checkpoint_path=ck2, fsync_checkpoints=True)
    for k in base:
        np.testing.assert_array_equal(base[k], out[k], err_msg=k)
    with pytest.raises(ValueError, match="fsync"):
        runner.run(CFG, eng, fsync_checkpoints=True)   # no checkpoint_path


def test_cli_fsync_requires_checkpoint(tmp_path, capsys):
    cli, flags = _cli_flags(extra=["--fsync-checkpoints"])
    with pytest.raises(SystemExit):
        cli.main(flags)
    cli2, flags2 = _cli_flags(tmp_path / "ck.npz", ["--fsync-checkpoints"])
    assert cli2.main(flags2) == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["digest"] == simulator.run(CFG, warmup=False).digest


# --- supervisor (tier-1) -----------------------------------------------------

def test_supervisor_retries_transient_and_resumes(tmp_path):
    ck = tmp_path / "ck.npz"
    base = simulator.run(CFG, warmup=False)
    # Dispatch 3 = the third chunk of attempt 1: the first two chunks
    # complete (rounds 0..16, checkpoints at 8 and 16), then the tunnel
    # "flakes"; the retry must resume at 16, not at 0.
    faults.install(transient_dispatches=[3])
    res = supervisor.supervised_run(CFG, retries=2, backoff_s=0,
                                    checkpoint_path=ck, sleep=lambda s: None)
    assert res.digest == base.digest
    rr = res.extras["run_report"]
    assert rr["n_attempts"] == 2
    assert rr["attempts"][0]["error"] is not None
    assert rr["attempts"][1]["error"] is None
    assert rr["attempts"][1]["start_round"] == 16
    assert rr["resumed_from_round"] == 16
    assert not rr["fallback_used"] and not rr["deadline_exceeded"]
    # A resumed run executes only the remaining rounds.
    assert res.node_round_steps == \
        CFG.n_sweeps * CFG.n_nodes * (CFG.n_rounds - 16)


def test_supervisor_backoff_jitter_bounded_and_seedable():
    """Backoff sleeps carry bounded multiplicative jitter — inside
    [base·2^k, base·2^k·(1+jitter)], deterministic for a seeded rng —
    so co-scheduled retries don't synchronize (docs/RESILIENCE.md)."""
    import random

    def delays_for(seed, jitter=0.25):
        faults.install(transient_dispatches=[1, 2, 3])
        got = []
        with pytest.raises(supervisor.SupervisorError):
            supervisor.supervised_run(CFG, retries=2, backoff_s=0.5,
                                      backoff_jitter=jitter,
                                      jitter_rng=random.Random(seed),
                                      sleep=got.append)
        return got

    d = delays_for(7)
    assert len(d) == 2
    assert 0.5 <= d[0] <= 0.5 * 1.25 and 1.0 <= d[1] <= 1.0 * 1.25
    assert d == delays_for(7)                   # seeded ⇒ reproducible
    assert d != delays_for(8)                   # ...and actually jittered
    assert delays_for(7, jitter=0.0) == [0.5, 1.0]  # opt-out: exact ladder
    with pytest.raises(ValueError, match="backoff_jitter"):
        supervisor.supervised_run(CFG, backoff_jitter=-0.1)


def test_supervisor_backoff_jitter_respects_cap():
    import random
    faults.install(transient_dispatches=[1, 2])
    got = []
    with pytest.raises(supervisor.SupervisorError):
        supervisor.supervised_run(CFG, retries=1, backoff_s=10.0,
                                  backoff_cap_s=1.0, backoff_jitter=0.5,
                                  jitter_rng=random.Random(3),
                                  sleep=got.append)
    assert got == [1.0]  # the cap is a hard ceiling, jitter included


def test_supervisor_resumes_crashing_run_bit_identical(tmp_path):
    """Fault-model composition, in-process: a transient failure mid-way
    through a run WITH the §6c adversary retries, resumes (the down
    mask rides the snapshot), and lands on the uninterrupted digest."""
    base = simulator.run(CRASH_CFG, warmup=False)
    faults.install(transient_dispatches=[3])
    res = supervisor.supervised_run(CRASH_CFG, retries=2, backoff_s=0,
                                    checkpoint_path=tmp_path / "ck.npz",
                                    sleep=lambda s: None)
    assert res.digest == base.digest
    assert res.extras["run_report"]["resumed_from_round"] == 16


def test_supervisor_gives_up_after_retries(tmp_path):
    faults.install(transient_dispatches=[1, 2, 3])
    with pytest.raises(supervisor.SupervisorError) as ei:
        supervisor.supervised_run(CFG, retries=2, backoff_s=0,
                                  checkpoint_path=tmp_path / "ck.npz",
                                  sleep=lambda s: None)
    rep = ei.value.report
    assert len(rep.attempts) == 3
    assert all(a.error for a in rep.attempts)


def test_supervisor_nontransient_raises_immediately(monkeypatch):
    calls = []

    def boom(cfg, **kw):
        calls.append(1)
        raise ValueError("bad config, retrying cannot help")

    monkeypatch.setattr(simulator, "run", boom)
    with pytest.raises(ValueError):
        supervisor.supervised_run(CFG, retries=5, backoff_s=0,
                                  sleep=lambda s: None)
    assert len(calls) == 1


def test_supervisor_deadline_gates_new_attempts(monkeypatch):
    def always_flaky(cfg, **kw):
        raise faults.InjectedTransientError("down")

    monkeypatch.setattr(simulator, "run", always_flaky)
    with pytest.raises(supervisor.SupervisorError, match="deadline"):
        supervisor.supervised_run(CFG, retries=50, backoff_s=0.4,
                                  deadline_s=0.2)
    # and with fallback enabled the same exhaustion degrades instead
    monkeypatch.undo()


def test_supervisor_fallback_cpu_digest_equivalent(monkeypatch):
    base = simulator.run(CFG, warmup=False)
    real_run = simulator.run

    def tpu_down(cfg, **kw):
        if cfg.engine == "tpu":
            raise faults.InjectedTransientError("tunnel down")
        return real_run(cfg, **kw)

    monkeypatch.setattr(simulator, "run", tpu_down)
    res = supervisor.supervised_run(CFG, retries=1, backoff_s=0,
                                    fallback_cpu=True, sleep=lambda s: None)
    rr = res.extras["run_report"]
    assert rr["fallback_used"] and rr["n_attempts"] == 2
    assert res.config.engine == "cpu"
    # Graceful degradation is sound: the oracle's decided logs are
    # byte-identical to the TPU engine's (the framework's acceptance
    # criterion) — the caller gets the SAME digest, just slowly.
    assert res.digest == base.digest


def test_supervisor_rejects_bad_usage():
    with pytest.raises(ValueError, match="retries"):
        supervisor.supervised_run(CFG, retries=-1)
    with pytest.raises(ValueError, match="fallback_cpu"):
        supervisor.supervised_run(
            dataclasses.replace(CFG, engine="cpu"), fallback_cpu=True)
    with pytest.raises(ValueError, match="checkpoint_path"):
        supervisor.supervised_run(
            dataclasses.replace(CFG, engine="cpu"), checkpoint_path="x.npz")
    # The oracle derives seeds from cfg.seed; degrading with an explicit
    # vector would silently swap trajectories under the caller.
    with pytest.raises(ValueError, match="seeds"):
        supervisor.supervised_run(
            CFG, fallback_cpu=True,
            seeds=np.arange(CFG.n_sweeps, dtype=np.uint32))


def test_is_transient_classification():
    assert supervisor.is_transient(faults.InjectedTransientError("x"))
    assert supervisor.is_transient(ConnectionResetError("tunnel"))
    assert supervisor.is_transient(TimeoutError("rpc"))
    assert not supervisor.is_transient(ValueError("bad flag"))
    assert not supervisor.is_transient(NotImplementedError("no engine"))

    class XlaRuntimeError(Exception):  # matched by name, as jaxlib's is
        pass

    assert supervisor.is_transient(XlaRuntimeError("DEADLINE_EXCEEDED"))


# --- CLI integration (tier-1) ------------------------------------------------

def _cli_flags(ck=None, extra=(), crash=False):
    from consensus_tpu import cli
    flags = ["--protocol", "raft", "--nodes", "5", "--rounds", "48",
             "--sweeps", "2", "--log-capacity", "16", "--max-entries", "8",
             "--scan-chunk", "8", "--drop-rate", "0.1",
             "--churn-rate", "0.05", "--engine", "tpu", "--platform", "cpu"]
    if crash:  # the SPEC §6c adversary, matching CRASH_CFG
        flags += ["--crash-prob", "0.15", "--recover-prob", "0.3"]
    if ck is not None:
        flags += ["--checkpoint", str(ck)]
    return cli, flags + list(extra)


def test_cli_supervised_run_reports_attempts(tmp_path, capsys):
    cli, flags = _cli_flags(tmp_path / "ck.npz", ["--retries", "1"])
    rc = cli.main(flags)
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    base = simulator.run(CFG, warmup=False)
    assert out["digest"] == base.digest
    assert out["attempts"] == 1
    assert out["resumed_from_round"] == 0
    assert out["fallback_used"] is False


def test_cli_rejects_supervision_on_cpu_engine():
    from consensus_tpu import cli
    for extra in (["--retries", "2"], ["--deadline", "5"],
                  ["--fallback-cpu"], ["--keep-checkpoints", "3"]):
        with pytest.raises(SystemExit):
            cli.main(["--protocol", "raft", "--engine", "cpu"] + extra)


def test_cli_rejects_keep_checkpoints_without_checkpoint():
    cli, flags = _cli_flags(extra=["--keep-checkpoints", "3"])
    with pytest.raises(SystemExit):
        cli.main(flags)


def test_cli_rejects_supervision_with_fsweep_and_profile(tmp_path):
    from consensus_tpu import cli
    with pytest.raises(SystemExit):
        cli.main(["--protocol", "pbft", "--engine", "tpu",
                  "--f-sweep", "1,2", "--retries", "2"])
    cli2, flags = _cli_flags(tmp_path / "ck.npz",
                             ["--retries", "1", "--profile",
                              str(tmp_path / "trace")])
    with pytest.raises(SystemExit):
        cli2.main(flags)


# --- subprocess crash injection (slow tier) ----------------------------------

def _spawn_cli(ck, fault_plan=None, extra=(), crash=False):
    cli, flags = _cli_flags(ck, extra, crash=crash)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    if fault_plan is not None:
        env[faults.ENV_VAR] = json.dumps(fault_plan)
    return subprocess.run(
        [sys.executable, "-m", "consensus_tpu"] + flags,
        capture_output=True, text=True, env=env,
        cwd=pathlib.Path(__file__).resolve().parents[1], timeout=600)


@pytest.mark.slow
def test_sigkill_midrun_then_resume_is_bit_identical(tmp_path):
    """THE crash-recovery proof (acceptance criteria): a checkpointed CLI
    run is SIGKILLed by the fault harness after chunk 2; the supervisor
    resumes from the newest valid snapshot and the final digest is
    bit-identical to an uninterrupted run. Then the latest snapshot is
    corrupted and a second recovery falls back to the previous rotation
    — still bit-identical."""
    ck = tmp_path / "ck.npz"
    p = _spawn_cli(ck, fault_plan={"kill_after_chunk": 2},
                   extra=["--keep-checkpoints", "3"])
    assert p.returncode == -signal.SIGKILL, (p.returncode, p.stderr)
    # The kill landed after the chunk-2 checkpoint was durably written.
    assert runner.peek_checkpoint(ck, CFG) == 16

    base = simulator.run(CFG, warmup=False)
    res = supervisor.supervised_run(CFG, checkpoint_path=ck, retries=0)
    assert res.digest == base.digest
    assert res.extras["run_report"]["resumed_from_round"] == 16

    # Corruption half, against the files the resumed run just rotated:
    # damage the newest snapshot; recovery must use the previous rung.
    newest = runner.peek_checkpoint(ck, CFG)
    faults.corrupt_checkpoint(ck, "truncate")
    fell_back_to = runner.peek_checkpoint(ck, CFG)
    assert fell_back_to is not None and fell_back_to < newest
    res2 = supervisor.supervised_run(CFG, checkpoint_path=ck, retries=0)
    assert res2.digest == base.digest
    assert res2.extras["run_report"]["resumed_from_round"] == fell_back_to


@pytest.mark.slow
def test_sigkill_midrun_with_crash_adversary_is_bit_identical(tmp_path):
    """Fault-model composition, end to end: a CLI run WITH the §6c
    crash-recover adversary is SIGKILLed after chunk 2; the resumed run
    must be bit-identical to an uninterrupted one — the down mask and
    every frozen node's state ride the verified snapshot."""
    ck = tmp_path / "ck.npz"
    p = _spawn_cli(ck, fault_plan={"kill_after_chunk": 2}, crash=True,
                   extra=["--fsync-checkpoints"])
    assert p.returncode == -signal.SIGKILL, (p.returncode, p.stderr)
    assert runner.peek_checkpoint(ck, CRASH_CFG) == 16
    base = simulator.run(CRASH_CFG, warmup=False)
    res = supervisor.supervised_run(CRASH_CFG, checkpoint_path=ck, retries=0)
    assert res.digest == base.digest
    assert res.extras["run_report"]["resumed_from_round"] == 16


@pytest.mark.slow
def test_sigkill_mid_async_write_recovers_newest_valid(tmp_path):
    """Acceptance: a real SIGKILL DURING an in-flight async snapshot
    write — tmp bytes on disk, atomic rename not yet issued, fired on
    the WRITER thread while the chunk loop is already past the submit —
    is recovered by fallback-to-newest-valid: the torn write never
    becomes visible, the previous rotation resumes, and the digest is
    bit-identical to an uninterrupted run."""
    ck = tmp_path / "ck.npz"
    p = _spawn_cli(ck, fault_plan={"kill_mid_write": 2},
                   extra=["--keep-checkpoints", "3"])
    assert p.returncode == -signal.SIGKILL, (p.returncode, p.stderr)
    # Write 2 (round 16) died pre-rename: its tmp is orphaned on disk
    # and the newest VALID snapshot is write 1 (round 8).
    assert (tmp_path / "ck.tmp.npz").exists()
    assert runner.peek_checkpoint(ck, CFG) == 8

    base = simulator.run(CFG, warmup=False)
    res = supervisor.supervised_run(CFG, checkpoint_path=ck, retries=0)
    assert res.digest == base.digest
    assert res.extras["run_report"]["resumed_from_round"] == 8


@pytest.mark.slow
def test_cli_retries_transient_fault_end_to_end(tmp_path):
    """A child `python -m consensus_tpu --retries 2` hit by an injected
    transient error on dispatch 3 must retry, resume from round 16, and
    report the same digest as an uninterrupted run."""
    ck = tmp_path / "ck.npz"
    p = _spawn_cli(ck, fault_plan={"transient_dispatches": [3]},
                   extra=["--retries", "2"])
    assert p.returncode == 0, p.stderr
    out = json.loads(p.stdout.strip().splitlines()[-1])
    base = simulator.run(CFG, warmup=False)
    assert out["digest"] == base.digest
    assert out["attempts"] == 2
    assert out["resumed_from_round"] == 16
    assert out["fallback_used"] is False


@pytest.mark.slow
def test_sigkill_without_supervisor_plain_cli_resume(tmp_path):
    """Resume also works through the plain (unsupervised) CLI path: a
    second identical invocation picks up the dead run's snapshot."""
    ck = tmp_path / "ck.npz"
    p = _spawn_cli(ck, fault_plan={"kill_after_chunk": 3})
    assert p.returncode == -signal.SIGKILL, (p.returncode, p.stderr)
    p2 = _spawn_cli(ck)
    assert p2.returncode == 0, p2.stderr
    out = json.loads(p2.stdout.strip().splitlines()[-1])
    base = simulator.run(CFG, warmup=False)
    assert out["digest"] == base.digest
    # steps cover only the resumed rounds (24..48), not the dead run's.
    assert out["steps"] == CFG.n_sweeps * CFG.n_nodes * (CFG.n_rounds - 24)


# --- grouped-sweep SIGKILL resume (slow tier) --------------------------------

GROUPED_CFG = dataclasses.replace(CFG, n_rounds=24, n_sweeps=4,
                                  sweep_chunk=3)


def _spawn_grouped_cli(root, fault_plan=None, extra=()):
    flags = ["--protocol", "raft", "--nodes", "5", "--rounds", "24",
             "--sweeps", "4", "--sweep-chunk", "3", "--log-capacity", "16",
             "--max-entries", "8", "--scan-chunk", "8",
             "--drop-rate", "0.1", "--churn-rate", "0.05",
             "--engine", "tpu", "--platform", "cpu",
             "--group-dir", str(root)] + list(extra)
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    if fault_plan is not None:
        env[faults.ENV_VAR] = json.dumps(fault_plan)
    return subprocess.run(
        [sys.executable, "-m", "consensus_tpu"] + flags,
        capture_output=True, text=True, env=env,
        cwd=pathlib.Path(__file__).resolve().parents[1], timeout=600)


@pytest.mark.slow
def test_sigkill_grouped_sweep_resumes_from_group_manifest(tmp_path):
    """The grouped-resume acceptance proof: a --group-dir CLI run (4
    sweeps in groups of 3 -> 2 groups, 3 chunks each) is SIGKILLed by
    the fault harness during group 1; the supervised re-run reads the
    group manifest, SKIPS completed group 0 via its final snapshot,
    resumes group 1 mid-scan from its own rotation set, and the digest
    is bit-identical to an uninterrupted run."""
    root = tmp_path / "groups"
    # Chunks 1-3 are group 0 (rounds 8/16/24 + final snapshot); the
    # kill lands after group 1's first chunk and its r=8 snapshot.
    p = _spawn_grouped_cli(root, fault_plan={"kill_after_chunk": 4})
    assert p.returncode == -signal.SIGKILL, (p.returncode, p.stderr)
    groups = runner._sweep_groups(GROUPED_CFG)
    sub0, s0 = groups[0]
    sub1, s1 = groups[1]
    assert runner.peek_checkpoint(
        runner.group_checkpoint_path(root, 0), sub0, seeds=s0) == 24
    assert runner.peek_checkpoint(
        runner.group_checkpoint_path(root, 1), sub1, seeds=s1) == 8
    # The manifest recorded exactly the completed group.
    assert runner.read_group_manifest(root, GROUPED_CFG) == [0]

    base = simulator.run(dataclasses.replace(GROUPED_CFG, sweep_chunk=0),
                         warmup=False)
    res = supervisor.supervised_run(GROUPED_CFG, group_dir=root, retries=0)
    assert res.digest == base.digest
    # And through the CLI front door (idempotent second recovery).
    p2 = _spawn_grouped_cli(root, extra=["--retries", "1"])
    assert p2.returncode == 0, p2.stderr
    out = json.loads(p2.stdout.strip().splitlines()[-1])
    assert out["digest"] == base.digest
