"""Observability & determinism (SURVEY.md §5).

The simulator is a pure function of (config, seed): reruns must be
bitwise identical — this is the framework's race-detection story (races
are designed out; a nondeterministic rerun would expose one), and the
digest is the O(1) equivalence handle the reference's decided-log
comparison becomes.
"""
import json

from consensus_tpu.core.config import Config
from consensus_tpu.network import simulator


CFG = Config(protocol="raft", engine="tpu", n_nodes=5, n_rounds=48,
             n_sweeps=2, log_capacity=32, max_entries=16,
             drop_rate=0.1, partition_rate=0.05, churn_rate=0.05)


def test_rerun_determinism():
    a = simulator.run(CFG, warmup=False)
    b = simulator.run(CFG, warmup=False)
    assert a.payload == b.payload
    assert a.digest == b.digest


def test_run_result_metrics():
    r = simulator.run(CFG, warmup=False)
    assert r.node_round_steps == 2 * 5 * 48
    assert r.wall_s > 0
    assert r.steps_per_sec > 0
    assert len(r.digest) == 64


def test_config_json_roundtrip_stable():
    s = CFG.to_json()
    cfg2 = Config.from_json(s)
    assert cfg2 == CFG
    # cutoffs recorded for humans, re-derived on load
    assert json.loads(s)["_cutoffs"]["drop"] == CFG.drop_cutoff


def test_seed_changes_digest():
    import dataclasses
    a = simulator.run(CFG, warmup=False)
    b = simulator.run(dataclasses.replace(CFG, seed=CFG.seed + 1), warmup=False)
    assert a.digest != b.digest
