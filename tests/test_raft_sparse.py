"""SPEC §3b capped-Raft engine (engines/raft_sparse.py): differential
byte-equivalence vs the C++ oracle's capped scalar twin, dense-equivalence
when the cap is not binding, and mesh-sharded digest invariance.

The capped engine is the 100k-node path (BASELINE.json:5); these tests pin
its semantics at small N where the full [N, N] oracle is cheap.
"""
import numpy as np
import pytest

from consensus_tpu import Config
from consensus_tpu.network import simulator
from consensus_tpu.parallel.mesh import make_mesh


def _cfg(**kw):
    base = dict(protocol="raft", n_nodes=7, n_rounds=96, log_capacity=64,
                max_entries=40, n_sweeps=2, seed=123,
                drop_rate=0.1, partition_rate=0.05, churn_rate=0.05)
    base.update(kw)
    return Config(**base)


CONFIGS = [
    # (tag, config) — adversarial coverage mirrors the dense suite.
    ("small-cap", _cfg(max_active=2)),
    ("mid-cap", _cfg(max_active=3)),
    ("full-cap", _cfg(max_active=7)),
    ("quiet", _cfg(max_active=3, drop_rate=0.0, partition_rate=0.0,
                   churn_rate=0.0)),
    ("hostile", _cfg(max_active=4, n_nodes=9, n_rounds=128, drop_rate=0.3,
                     partition_rate=0.2, churn_rate=0.1, seed=7)),
    ("bigger", _cfg(max_active=4, n_nodes=33, n_rounds=64, seed=5)),
    # A*N = 8*640 > _SMALL_PICK: drives _pick_row's one-hot-reduce path
    # (what raft-100k runs) through the oracle differential, not just
    # the small-shape gather fallback.
    ("reduce-path", _cfg(max_active=8, n_nodes=640, n_rounds=48,
                         n_sweeps=1, seed=29)),
    # A=20 > 16: _rows_from_small's row-gather fallback (the select
    # chain is only used at small static A) gets differential coverage.
    ("wide-cap", _cfg(max_active=20, n_nodes=100, n_rounds=48,
                      n_sweeps=1, seed=37)),
]


@pytest.mark.parametrize("tag,cfg", CONFIGS, ids=[t for t, _ in CONFIGS])
def test_sparse_differential_vs_oracle(tag, cfg):
    tpu = simulator.run(cfg)
    cpu = simulator.run(Config(**{**cfg.__dict__, "engine": "cpu"}))
    assert tpu.payload == cpu.payload, (tag, tpu.digest, cpu.digest)


def test_capped_equals_dense_when_cap_not_binding():
    """With A = N every candidate/leader is active and tracked, so the
    §3b engine must reproduce the dense §3 decided logs bit-for-bit."""
    dense = simulator.run(_cfg())
    capped = simulator.run(_cfg(max_active=7))
    assert dense.payload == capped.payload, (dense.digest, capped.digest)


def test_capped_equals_dense_with_headroom():
    """A below N but above the realized concurrent-sender count: randomized
    timeouts over t in [3, 8) make >4 simultaneous candidates vanishingly
    rare at N=7, and the capped engine is exact whenever the cap never
    binds. The quiet config has no churn, so leadership is stable."""
    quiet = dict(drop_rate=0.02, partition_rate=0.0, churn_rate=0.0, seed=31)
    dense = simulator.run(_cfg(**quiet))
    capped = simulator.run(_cfg(max_active=4, **quiet))
    assert dense.payload == capped.payload


def test_sparse_mesh_sharded_digest_invariant():
    """The §3b pspec under a real ("sweep", "node") mesh: GSPMD partitioning
    must not change a single decided byte."""
    cfg = _cfg(max_active=3, n_nodes=8, n_sweeps=2)
    plain = simulator.run(cfg)
    sharded = simulator.run(cfg, mesh=make_mesh((2, 4)))
    assert plain.payload == sharded.payload


def test_sparse_blocked_scan_bit_identical():
    cfg = _cfg(max_active=3)
    whole = simulator.run(cfg)
    chunked = simulator.run(Config(**{**cfg.__dict__, "scan_chunk": 13}))
    assert whole.payload == chunked.payload


def test_max_active_validation():
    with pytest.raises(ValueError):
        _cfg(max_active=8)  # > n_nodes
    with pytest.raises(ValueError):
        _cfg(max_active=-1)
