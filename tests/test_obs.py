"""Unified observability layer (docs/OBSERVABILITY.md).

Three contracts under test:

  1. **Digest neutrality** — on-device telemetry enabled vs disabled
     yields bit-identical payloads for every engine family, and both
     match the CPU oracle (telemetry reads the state update, never
     feeds it).
  2. **Counter soundness** — monotone protocol quantities accumulated
     per round must equal the same quantity read off the final state
     (entries_committed == Σ commit, blocks_appended == Σ chain_len,
     ...), and must be invariant to scan chunking / sweep grouping.
  3. **Artifact schemas** — trace JSONL and metrics snapshots written
     by a real CLI run validate under tools/validate_trace.py (run as
     a subprocess, exactly as CI would).
"""
import dataclasses
import importlib.util
import json
import pathlib
import subprocess
import sys

import numpy as np
import pytest

from consensus_tpu.core.config import Config
from consensus_tpu.network import simulator
from consensus_tpu.obs import metrics as obs_metrics
from consensus_tpu.obs import trace as obs_trace

from helpers import run_cached

REPO = pathlib.Path(__file__).resolve().parents[1]
ADV = dict(drop_rate=0.1, partition_rate=0.05, churn_rate=0.05)

CFGS = {
    "raft": Config(protocol="raft", n_nodes=5, n_rounds=48, n_sweeps=2,
                   log_capacity=32, max_entries=16, **ADV),
    "pbft": Config(protocol="pbft", f=1, n_nodes=4, n_rounds=24,
                   log_capacity=8, **ADV),
    "paxos": Config(protocol="paxos", n_nodes=7, n_rounds=24,
                    log_capacity=8, **ADV),
    "dpos": Config(protocol="dpos", n_nodes=24, n_rounds=32,
                   log_capacity=48, n_candidates=8, n_producers=3,
                   epoch_len=8, **ADV),
}
# The large-N variant engines (SPEC §3b / §6b) carry their own kernels —
# telemetry must hold there too.
VARIANTS = {
    "raft-sparse": Config(protocol="raft", n_nodes=64, max_active=4,
                          n_rounds=32, n_sweeps=2, log_capacity=16,
                          max_entries=8, **ADV),
    "pbft-bcast": Config(protocol="pbft", fault_model="bcast", f=5,
                         n_nodes=16, n_rounds=24, log_capacity=8, **ADV),
}


def _run_telem(cfg, **kw):
    return simulator.run(cfg, warmup=False, telemetry=True, **kw)


# --- 1. digest neutrality ---------------------------------------------------

@pytest.mark.parametrize("proto", list(CFGS))
def test_telemetry_digest_neutral_vs_tpu_and_oracle(proto):
    cfg = CFGS[proto]
    on = _run_telem(cfg)
    assert on.payload == run_cached(cfg).payload
    # ... and the telemetry run still matches the C++ oracle byte-ish
    # (the framework's acceptance criterion survives instrumentation).
    assert on.payload == run_cached(
        dataclasses.replace(cfg, engine="cpu")).payload
    tel = on.extras["telemetry"]
    assert set(tel["totals"]) == set(tel["per_sweep"]) == set(tel["names"])
    for name, arr in tel["per_sweep"].items():
        assert arr.shape == (cfg.n_sweeps,)
        assert (arr >= 0).all(), name


@pytest.mark.parametrize("name", list(VARIANTS))
def test_telemetry_digest_neutral_variant_engines(name):
    cfg = VARIANTS[name]
    assert _run_telem(cfg).payload == run_cached(cfg).payload


# --- 2. counter soundness ---------------------------------------------------

def test_raft_entries_committed_matches_final_state():
    r = _run_telem(CFGS["raft"])
    # commit indices start at 0 and only advance; the accumulated
    # per-round advance must equal the final commit indices, per sweep.
    np.testing.assert_array_equal(
        r.extras["telemetry"]["per_sweep"]["entries_committed"],
        r.counts.sum(axis=1))
    assert r.extras["telemetry"]["totals"]["leader_elections"] >= 1


def test_pbft_commit_paths_partition_final_committed():
    r = _run_telem(CFGS["pbft"])
    per = r.extras["telemetry"]["per_sweep"]
    # Every committed (node, slot) was reached exactly once, via its own
    # 2f+1 tally or via decide gossip — the two counters partition the
    # final committed count.
    np.testing.assert_array_equal(
        per["commit_quorums"] + per["commits_adopted"],
        r.counts.sum(axis=1))


def test_paxos_values_learned_matches_final_state():
    r = _run_telem(CFGS["paxos"])
    np.testing.assert_array_equal(
        r.extras["telemetry"]["per_sweep"]["values_learned"],
        r.counts.sum(axis=1))


def test_dpos_blocks_appended_matches_final_state():
    cfg = CFGS["dpos"]
    r = _run_telem(cfg)
    per = r.extras["telemetry"]["per_sweep"]
    np.testing.assert_array_equal(per["blocks_appended"],
                                  r.counts.sum(axis=1))
    np.testing.assert_array_equal(
        per["blocks_appended"] + per["missed_appends"],
        np.full(cfg.n_sweeps, cfg.n_nodes * cfg.n_rounds))


@pytest.mark.parametrize("repl", [dict(scan_chunk=7), dict(sweep_chunk=1)],
                         ids=["scan_chunk", "sweep_chunk"])
def test_telemetry_invariant_to_chunking(repl):
    base = _run_telem(CFGS["raft"])
    got = _run_telem(dataclasses.replace(CFGS["raft"], **repl))
    assert got.payload == base.payload
    for k, v in base.extras["telemetry"]["per_sweep"].items():
        np.testing.assert_array_equal(
            got.extras["telemetry"]["per_sweep"][k], v, err_msg=k)


def test_runner_rejects_telemetry_without_stats():
    from consensus_tpu.network import runner
    with pytest.raises(ValueError, match="stats"):
        runner.run(CFGS["raft"], simulator.engine_def(CFGS["raft"]),
                   telemetry=True)


# --- checkpoint IO accounting (recorded even with tracing off) --------------

def test_checkpoint_io_recorded_in_extras(tmp_path):
    ck = tmp_path / "ck.npz"
    cfg = dataclasses.replace(CFGS["raft"], scan_chunk=16)
    r = simulator.run(cfg, warmup=False, checkpoint_path=str(ck),
                      resume=True)
    io = r.extras["checkpoint_io"]
    assert io["saves"] == 2  # saves at r=16, 32 (never after the last chunk)
    assert io["bytes_written"] > 0 and io["save_s"] > 0
    assert io["loads"] == 0
    assert r.payload == run_cached(CFGS["raft"]).payload
    # A resumed run counts the load side.
    r2 = simulator.run(cfg, warmup=False, checkpoint_path=str(ck),
                       resume=True)
    io2 = r2.extras["checkpoint_io"]
    assert io2["loads"] == 1 and io2["bytes_read"] > 0
    assert r2.payload == r.payload


# --- trace + metrics sinks --------------------------------------------------

def _load_validator():
    spec = importlib.util.spec_from_file_location(
        "validate_trace", REPO / "tools" / "validate_trace.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_trace_jsonl_schema(tmp_path):
    path = tmp_path / "t.jsonl"
    obs_trace.configure(str(path))
    try:
        with obs_trace.span("outer", k=1) as sp:
            assert sp is not None
            sp["bytes"] = np.int64(7)  # numpy scalars must serialize
            with obs_trace.span("inner"):
                pass
        obs_trace.event("ev", why="test")
    finally:
        obs_trace.close()
    lines = [json.loads(x) for x in path.read_text().splitlines()]
    assert [x["type"] for x in lines] == ["meta", "span", "span", "event"]
    # Spans are sequenced at close: inner before outer.
    assert [x.get("name") for x in lines[1:]] == ["inner", "outer", "ev"]
    assert lines[2]["attrs"] == {"k": 1, "bytes": 7}
    assert _load_validator().validate_trace(path) == []


def test_trace_disabled_is_noop(tmp_path):
    obs_trace.close()
    with obs_trace.span("x") as sp:
        assert sp is None  # fast path: no record allocated
    obs_trace.event("y")   # must not raise


def test_trace_suspended_and_metrics_paused(tmp_path):
    path = tmp_path / "t.jsonl"
    obs_trace.configure(str(path))
    try:
        with obs_trace.span("outer"):
            with obs_trace.suspended():
                with obs_trace.span("hidden"):
                    pass
                obs_trace.event("hidden_ev")
    finally:
        obs_trace.close()
    names = [json.loads(x).get("name")
             for x in path.read_text().splitlines()[1:]]
    assert names == ["outer"]  # suspended block emitted nothing
    reg = obs_metrics.Registry()
    with obs_metrics.paused():
        reg.counter("c").inc()
        reg.histogram("h").observe(1.0)
        reg.gauge("g").set(5)
    reg.counter("c").inc()
    snap = reg.snapshot()
    assert snap["c"]["value"] == 1
    assert snap["h"]["count"] == 0 and snap["g"]["value"] == 0


def test_metrics_registry_and_prometheus():
    reg = obs_metrics.Registry()
    reg.counter("c").inc()
    reg.counter("c").inc(2)
    reg.gauge("g").set(1.5)
    h = reg.histogram("h", buckets=(0.1, 1.0))
    for v in (0.05, 0.5, 5.0):
        h.observe(v)
    snap = reg.snapshot()
    assert snap["c"] == {"type": "counter", "value": 3}
    assert snap["h"]["counts"] == [1, 1, 1]
    assert snap["h"]["count"] == 3
    with pytest.raises(TypeError):
        reg.gauge("c")  # type shadowing is an error
    with pytest.raises(ValueError):
        reg.counter("c").inc(-1)
    prom = reg.to_prometheus()
    assert '# TYPE c counter' in prom and 'h_bucket{le="+Inf"} 3' in prom


def test_metrics_snapshot_validates(tmp_path):
    reg = obs_metrics.Registry()
    reg.counter("a").inc(4)
    reg.histogram("b").observe(0.2)
    p = tmp_path / "m.json"
    p.write_text(json.dumps({"version": obs_metrics.SCHEMA_VERSION,
                             "metrics": reg.snapshot()}))
    assert _load_validator().validate_metrics(p) == []


def test_validator_flags_drift(tmp_path):
    v = _load_validator()
    bad = tmp_path / "bad.jsonl"
    bad.write_text('{"type": "span", "name": "x"}\n')  # no meta, no t_s
    assert v.validate_trace(bad)
    badm = tmp_path / "bad.json"
    badm.write_text(json.dumps({"version": 1, "metrics":
                                {"c": {"type": "counter", "value": -1}}}))
    assert v.validate_metrics(badm)


def test_supervisor_rejects_telemetry_on_cpu_engine():
    from consensus_tpu.network import supervisor
    with pytest.raises(ValueError, match="telemetry"):
        supervisor.supervised_run(
            dataclasses.replace(CFGS["raft"], engine="cpu"), telemetry=True)


def test_run_report_to_json_roundtrip():
    from consensus_tpu.network import supervisor
    result = supervisor.supervised_run(CFGS["raft"], retries=0,
                                       telemetry=True)
    assert result.extras["telemetry"]["totals"]["entries_committed"] > 0
    report = supervisor.RunReport(
        retries=1, attempts=[supervisor.Attempt(0, 0, 0.25, error="boom"),
                             supervisor.Attempt(1, 16, 0.5)],
        resumed_from_round=16)
    d = json.loads(report.to_json())
    assert d["n_attempts"] == 2
    assert d["attempts"][0]["wall_s"] == 0.25
    assert d["attempts"][1]["start_round"] == 16


# --- CI seam: a fresh CLI run's artifacts pass the validator ----------------

def test_cli_artifacts_validate_and_digest_stable(tmp_path, capsys):
    from consensus_tpu import cli
    flags = ["--protocol", "raft", "--nodes", "5", "--rounds", "32",
             "--sweeps", "2", "--log-capacity", "16", "--max-entries", "8",
             "--drop-rate", "0.1", "--engine", "tpu", "--scan-chunk", "8"]
    trace = tmp_path / "run.trace.jsonl"
    metrics = tmp_path / "metrics.json"
    rc = cli.main(flags + ["--telemetry", "--trace-out", str(trace),
                           "--metrics-out", str(metrics)])
    assert rc == 0
    with_tel = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    rc = cli.main(flags)
    assert rc == 0
    plain = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert with_tel["digest"] == plain["digest"]
    assert with_tel["telemetry"]["entries_committed"] >= 0

    # The CI tripwire, exactly as CI runs it: subprocess, nonzero on
    # drift. validate_trace.py imports neither jax nor the framework,
    # so the subprocess is cheap.
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "validate_trace.py"),
         "--trace", str(trace), "--metrics", str(metrics)],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    doc = json.loads(metrics.read_text())
    assert doc["metrics"]["dispatch_wall_s"]["count"] >= 4  # 32/8 chunks


def test_cli_crash_adversary_artifacts_validate(tmp_path, capsys):
    """A fresh CLI run with the SPEC §6c crash-recover adversary enabled
    must emit artifacts the validator accepts — including the new
    telemetry counter names (crashes/recoveries/nodes_down) in the CLI
    report, checked against the validator's known-name registry."""
    from consensus_tpu import cli
    trace = tmp_path / "run.trace.jsonl"
    metrics = tmp_path / "metrics.json"
    rc = cli.main(["--protocol", "raft", "--nodes", "5", "--rounds", "32",
                   "--sweeps", "2", "--log-capacity", "16",
                   "--max-entries", "8", "--drop-rate", "0.1",
                   "--crash-prob", "0.2", "--recover-prob", "0.3",
                   "--max-crashed", "2", "--engine", "tpu",
                   "--scan-chunk", "8", "--telemetry",
                   "--trace-out", str(trace), "--metrics-out", str(metrics)])
    assert rc == 0
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert report["telemetry"]["crashes"] > 0
    assert report["telemetry"]["nodes_down"] >= report["telemetry"]["crashes"]
    cli_report = tmp_path / "report.json"
    cli_report.write_text(json.dumps(report))
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "validate_trace.py"),
         "--trace", str(trace), "--metrics", str(metrics),
         "--cli-report", str(cli_report)],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr


def test_validator_flags_unknown_telemetry_counter(tmp_path):
    v = _load_validator()
    good = tmp_path / "r.json"
    good.write_text(json.dumps({
        "protocol": "raft", "engine": "tpu", "digest": "d", "steps": 1,
        "wall_s": 0.1, "payload_bytes": 8,
        "telemetry": {"crashes": 0, "recoveries": 0, "nodes_down": 0}}))
    assert v.validate_cli_report(good) == []
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({
        "protocol": "raft", "engine": "tpu", "digest": "d", "steps": 1,
        "wall_s": 0.1, "payload_bytes": 8,
        "telemetry": {"crashez": 1, "crashes": -1}}))
    errs = v.validate_cli_report(bad)
    assert any("crashez" in e for e in errs)
    assert any("crashes" in e and ">= 0" in e for e in errs)


def test_cli_async_checkpoint_artifacts_validate(tmp_path, capsys):
    """A fresh async-checkpointing CLI run's artifacts pass the
    validator — including the writer spans asserted present via
    --expect-spans and the extended checkpoint_io block in the CLI
    report (exactly as CI would run it: subprocess, nonzero on drift)."""
    from consensus_tpu import cli
    trace = tmp_path / "run.trace.jsonl"
    metrics = tmp_path / "metrics.json"
    rc = cli.main(["--protocol", "raft", "--nodes", "5", "--rounds", "32",
                   "--sweeps", "2", "--log-capacity", "16",
                   "--max-entries", "8", "--drop-rate", "0.1",
                   "--engine", "tpu", "--scan-chunk", "8",
                   "--checkpoint", str(tmp_path / "ck.npz"),
                   "--trace-out", str(trace),
                   "--metrics-out", str(metrics)])
    assert rc == 0
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    io = report["checkpoint_io"]
    assert io["saves"] == 3 and io["save_hidden_s"] > 0
    cli_report = tmp_path / "report.json"
    cli_report.write_text(json.dumps(report))
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "validate_trace.py"),
         "--trace", str(trace), "--metrics", str(metrics),
         "--cli-report", str(cli_report),
         "--expect-spans", "ckpt_snapshot,ckpt_write"],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    # The metrics snapshot carries the new writer instruments.
    doc = json.loads(metrics.read_text())
    assert doc["metrics"]["checkpoint_hidden_s"]["count"] >= 3
    assert doc["metrics"]["checkpoint_backpressure_s"]["count"] >= 3

    v = _load_validator()
    # Field drift in checkpoint_io trips the registry — both ways.
    bad = dict(report)
    bad["checkpoint_io"] = {**io, "weird_s": 1.0}
    b = tmp_path / "bad.json"
    b.write_text(json.dumps(bad))
    assert any("weird_s" in e for e in v.validate_cli_report(b))
    bad["checkpoint_io"] = {k: x for k, x in io.items() if k != "pull_s"}
    b.write_text(json.dumps(bad))
    assert any("pull_s" in e for e in v.validate_cli_report(b))
    # --expect-spans fails when the writer spans are absent (e.g. the
    # trace of a --sync-checkpoints run).
    assert v.validate_expected_spans(trace, ["ckpt_snapshot"]) == []
    assert v.validate_expected_spans(trace, ["nonsense_span"])
    # --expect-events: registered-name + presence checks both bite
    # (nothing failed in this run, so the error event is rightly absent).
    assert v.validate_expected_events(trace, ["nonsense_ev"])
    assert v.validate_expected_events(trace, ["checkpoint_write_failed"])


def test_cli_sync_checkpoint_trace_has_no_writer_spans(tmp_path, capsys):
    """--sync-checkpoints restores the pre-async trace shape: saves
    appear as checkpoint_save spans on the hot path and --expect-spans
    for the writer spans correctly fails."""
    from consensus_tpu import cli
    trace = tmp_path / "t.jsonl"
    rc = cli.main(["--protocol", "raft", "--nodes", "5", "--rounds", "32",
                   "--log-capacity", "16", "--max-entries", "8",
                   "--engine", "tpu", "--scan-chunk", "8",
                   "--checkpoint", str(tmp_path / "ck.npz"),
                   "--sync-checkpoints", "--trace-out", str(trace)])
    assert rc == 0
    capsys.readouterr()
    names = [json.loads(x).get("name")
             for x in trace.read_text().splitlines()[1:]]
    assert names.count("checkpoint_save") == 3
    assert "ckpt_snapshot" not in names and "ckpt_write" not in names
    v = _load_validator()
    errs = v.validate_expected_spans(trace, ["ckpt_snapshot", "ckpt_write"])
    assert len(errs) == 2


def test_cli_artifacts_exclude_warmup(tmp_path, capsys):
    """The hidden warmup pass (compile) must not pollute exported
    artifacts: dispatch_wall_s counts exactly the timed run's chunks,
    and the trace shows one 'warmup' span, not its inner dispatches."""
    from consensus_tpu import cli
    obs_metrics.reset()  # the default registry is process-cumulative
    trace = tmp_path / "t.jsonl"
    metrics = tmp_path / "m.json"
    rc = cli.main(["--protocol", "raft", "--nodes", "5", "--rounds", "32",
                   "--log-capacity", "16", "--max-entries", "8",
                   "--engine", "tpu", "--scan-chunk", "8",
                   "--trace-out", str(trace), "--metrics-out", str(metrics)])
    assert rc == 0
    capsys.readouterr()
    doc = json.loads(metrics.read_text())
    assert doc["metrics"]["dispatch_wall_s"]["count"] == 4  # 32/8, once
    names = [json.loads(x).get("name")
             for x in trace.read_text().splitlines()[1:]]
    assert names.count("warmup") == 1
    assert names.count("dispatch") == 4


def test_cli_failed_supervised_run_still_writes_artifacts(tmp_path, capsys):
    """When every attempt fails, --metrics-out and the RunReport dump
    must still land — they are the failure-diagnosis artifacts."""
    from consensus_tpu import cli
    from consensus_tpu.network import faults, supervisor
    metrics = tmp_path / "m.json"
    trace = tmp_path / "t.jsonl"
    faults.install(transient_dispatches=(1, 2))
    try:
        with pytest.raises(supervisor.SupervisorError):
            cli.main(["--protocol", "raft", "--nodes", "5", "--rounds", "8",
                      "--log-capacity", "8", "--max-entries", "4",
                      "--engine", "tpu", "--retries", "1",
                      "--trace-out", str(trace),
                      "--metrics-out", str(metrics)])
    finally:
        faults.reset()
    capsys.readouterr()
    report = tmp_path / "m.run_report.json"
    assert metrics.exists() and report.exists()
    assert _load_validator().validate_metrics(metrics) == []
    assert _load_validator().validate_report(report) == []
    # The retry record is in the trace too — the --expect-events
    # registry's positive case.
    assert _load_validator().validate_expected_events(
        trace, ["attempt_failed", "backoff"]) == []
    doc = json.loads(report.read_text())
    assert doc["n_attempts"] == 2
    assert all(a["error"] for a in doc["attempts"])


def test_cli_failed_unsupervised_run_still_writes_metrics(tmp_path, capsys):
    """Even without a supervisor, a run that dies mid-flight leaves its
    partial metrics snapshot (main's finally, not the success tail)."""
    from consensus_tpu import cli
    from consensus_tpu.network import faults
    metrics = tmp_path / "m.json"
    faults.install(transient_dispatches=(1,))
    try:
        with pytest.raises(faults.InjectedTransientError):
            cli.main(["--protocol", "raft", "--nodes", "5", "--rounds", "8",
                      "--log-capacity", "8", "--max-entries", "4",
                      "--engine", "tpu", "--metrics-out", str(metrics)])
    finally:
        faults.reset()
    capsys.readouterr()
    assert metrics.exists()
    assert _load_validator().validate_metrics(metrics) == []


def test_cli_metrics_write_failure_does_not_mask_run_error(tmp_path, capsys):
    """An artifact-write failure in main's finally must not replace the
    in-flight exception (the one being diagnosed) — but on a successful
    run a missing artifact still fails loudly."""
    from consensus_tpu import cli
    from consensus_tpu.network import faults
    gone = tmp_path / "removed-dir" / "m.json"  # parent doesn't exist
    flags = ["--protocol", "raft", "--nodes", "5", "--rounds", "8",
             "--log-capacity", "8", "--max-entries", "4",
             "--engine", "tpu", "--metrics-out", str(gone)]
    faults.install(transient_dispatches=(1,))
    try:
        with pytest.raises(faults.InjectedTransientError):
            cli.main(flags)  # the run's error wins; write failure -> stderr
    finally:
        faults.reset()
    assert "failed to write" in capsys.readouterr().err
    with pytest.raises(OSError):
        cli.main(flags)  # successful run, artifact missing -> loud
    capsys.readouterr()


def test_cli_prometheus_metrics_out(tmp_path, capsys):
    from consensus_tpu import cli
    prom = tmp_path / "metrics.prom"
    rc = cli.main(["--protocol", "paxos", "--nodes", "5", "--rounds", "8",
                   "--log-capacity", "4", "--engine", "tpu",
                   "--metrics-out", str(prom)])
    assert rc == 0
    capsys.readouterr()
    assert "# TYPE dispatch_wall_s histogram" in prom.read_text()
