"""Multi-chip efficiency evidence from compiled HLO (VERDICT r5 #2).

This environment has no second chip, but it has the next best thing: the
8-device virtual CPU mesh (conftest.py) runs the SAME GSPMD partitioner
that places collectives on a real v5e-8, and the compiled HLO text names
every collective it inserted. These tests lower the node-sharded round
loop through the production path (runner._chunk_jit, the exact jit the
benchmarks dispatch) and assert the communication *structure* the
north-star design claims (parallel/mesh.py):

  * node-sharded quorum tallies become local partial sums + small
    ALL-REDUCEs (the "quorum tallies psum'd across a device mesh"
    design) — the collective set stays in the all-reduce/reduce-scatter
    family;
  * no collective ever moves a full-carry operand: the §3b sparse
    engine's only all-gathers are O(N) tracked-set metadata, never the
    [N, L] log — a full-carry all-gather would mean GSPMD gave up on
    the sharding and the "scales by adding chips" claim is fiction;
  * sweep-axis sharding is embarrassingly parallel: ZERO collectives.

Numbers quoted from this census (e.g. 27 all-reduces, largest gather =
N elements) are compiler-version-dependent; the assertions below pin
the structural claims only.
"""
import re

import numpy as np
import pytest

from consensus_tpu.core.config import Config
from consensus_tpu.network import runner, simulator
from consensus_tpu.parallel.mesh import make_mesh

COLLECTIVE_RE = re.compile(
    r"= \(?([a-z0-9]+)\[([\d,]*)\][^\n]*? "
    r"(all-reduce|all-gather|reduce-scatter|all-to-all|collective-permute)\(")

# The raft-100k flagship semantics (SPEC §3b capped) at a mesh-divisible
# population — engine_def resolves this to raft_sparse, the engine whose
# multi-chip story the benchmarks depend on.
CAPPED = Config(protocol="raft", n_nodes=1024, n_rounds=8, n_sweeps=2,
                log_capacity=32, max_entries=24, max_active=8, seed=6,
                drop_rate=0.01, churn_rate=0.001)


def compiled_collectives(cfg: Config, mesh_shape) -> dict[str, list[int]]:
    """op name -> element counts of each collective's result operand, from
    the compiled (post-GSPMD) HLO of one production round-loop chunk."""
    eng = simulator.engine_def(cfg)
    mesh = make_mesh(mesh_shape)
    seeds = runner.make_seeds(cfg)
    carry = runner._init_jit(cfg, eng, seeds, mesh=mesh)
    lowered = runner._chunk_jit.lower(cfg, eng, cfg.n_rounds, carry,
                                      np.uint32(0), mesh=mesh)
    txt = lowered.compile().as_text()
    out: dict[str, list[int]] = {}
    for m in COLLECTIVE_RE.finditer(txt):
        shape = [int(x) for x in m.group(2).split(",") if x]
        out.setdefault(m.group(3), []).append(
            int(np.prod(shape)) if shape else 1)
    return out


def test_node_sharded_capped_raft_collective_family():
    colls = compiled_collectives(CAPPED, (2, 4))
    # The quorum reductions must actually cross the node axis — a census
    # with no all-reduce would mean the partitioner replicated the state
    # and the "mesh" is decorative.
    assert colls.get("all-reduce"), f"no all-reduce in census: {colls}"
    # The family claim: reshard/reduce traffic only. all-to-all or
    # collective-permute would signal a layout the design doesn't have.
    allowed = {"all-reduce", "reduce-scatter", "all-gather"}
    assert set(colls) <= allowed, f"unexpected collectives: {set(colls)}"


def test_node_sharded_capped_raft_no_full_carry_all_gather():
    cfg = CAPPED
    colls = compiled_collectives(cfg, (2, 4))
    gathers = colls.get("all-gather", [])
    # Smallest full-carry operand: ONE sweep's [N, L] log leaf. Every
    # gather must sit far below it (the §3b design only exchanges O(N)
    # tracked-set metadata; 2N leaves headroom for a fused pair while
    # still excluding any [N, L]-class or [A, N]-carry operand at L=32).
    full_leaf = cfg.n_nodes * cfg.log_capacity
    assert all(g <= 2 * cfg.n_nodes for g in gathers), gathers
    assert all(8 * g <= full_leaf for g in gathers), (gathers, full_leaf)
    # Same bound for the reduce family: a full-carry all-reduce would be
    # the same give-up in different clothes.
    for op, sizes in colls.items():
        assert all(8 * s <= full_leaf for s in sizes), (op, sizes)


def test_sweep_only_mesh_is_collective_free():
    # Sweeps are independent simulators — sharding ONLY the sweep axis
    # must compile to zero cross-device traffic (parallel/mesh.py).
    cfg = Config(protocol="raft", n_nodes=1024, n_rounds=8, n_sweeps=8,
                 log_capacity=32, max_entries=24, max_active=8, seed=6,
                 drop_rate=0.01, churn_rate=0.001)
    colls = compiled_collectives(cfg, (8,))
    assert not colls, f"sweep-parallel round emitted collectives: {colls}"


def test_node_sharded_digest_matches_unsharded():
    # The census proves efficiency; this pins correctness of the very
    # config it censused (GSPMD partitioning is digest-neutral).
    base = simulator.run(CAPPED)
    sharded = simulator.run(CAPPED, mesh=make_mesh((2, 4)))
    assert base.digest == sharded.digest
