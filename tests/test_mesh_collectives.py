"""Multi-chip efficiency evidence from compiled HLO (VERDICT r5 #2).

This environment has no second chip, but it has the next best thing: the
8-device virtual CPU mesh (conftest.py) runs the SAME GSPMD partitioner
that places collectives on a real v5e-8, and the compiled HLO text names
every collective it inserted. The census harness that began here is now
the library ``tools/hlocheck/hlo.py`` (`compiled_collectives`), which
lowers the node-sharded round loop through the production path
(runner._chunk_jit, the exact jit the benchmarks dispatch) — these tests
keep the original structural claims pinned in test form, while
``python -m tools.hlocheck`` enforces the same claims (and more) as
per-engine contracts with committed fingerprints:

  * node-sharded quorum tallies become local partial sums + small
    ALL-REDUCEs — the collective set stays in the all-reduce family;
  * no collective ever moves a full-carry operand: the §3b sparse
    engine's only all-gathers are O(N) tracked-set metadata, never the
    [N, L] log;
  * sweep-axis sharding is embarrassingly parallel: ZERO collectives.

Numbers quoted from this census are compiler-version-dependent; the
assertions pin the structural claims only (the fingerprint layer owns
drift detection — tools/hlocheck/fingerprint.py).
"""
import pathlib
import sys

from consensus_tpu.core.config import Config
from consensus_tpu.network import simulator
from consensus_tpu.parallel.mesh import make_mesh

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from tools.hlocheck.hlo import compiled_collectives  # noqa: E402
from tools.hlocheck.registry import CAPPED_1K as CAPPED  # noqa: E402


def test_node_sharded_capped_raft_collective_family():
    colls = compiled_collectives(CAPPED, (2, 4))
    # The quorum reductions must actually cross the node axis — a census
    # with no all-reduce would mean the partitioner replicated the state
    # and the "mesh" is decorative.
    assert colls.get("all-reduce"), f"no all-reduce in census: {colls}"
    # The family claim: reshard/reduce traffic only. all-to-all or
    # collective-permute would signal a layout the design doesn't have.
    allowed = {"all-reduce", "reduce-scatter", "all-gather"}
    assert set(colls) <= allowed, f"unexpected collectives: {set(colls)}"


def test_node_sharded_capped_raft_no_full_carry_all_gather():
    cfg = CAPPED
    colls = compiled_collectives(cfg, (2, 4))
    gathers = colls.get("all-gather", [])
    # Smallest full-carry operand: ONE sweep's [N, L] log leaf. Every
    # gather must sit far below it (the §3b design only exchanges O(N)
    # tracked-set metadata; 2N leaves headroom for a fused pair while
    # still excluding any [N, L]-class or [A, N]-carry operand at L=32).
    full_leaf = cfg.n_nodes * cfg.log_capacity
    assert all(g <= 2 * cfg.n_nodes for g in gathers), gathers
    assert all(8 * g <= full_leaf for g in gathers), (gathers, full_leaf)
    # Same bound for the reduce family: a full-carry all-reduce would be
    # the same give-up in different clothes.
    for op, sizes in colls.items():
        assert all(8 * s <= full_leaf for s in sizes), (op, sizes)


def test_sweep_only_mesh_is_collective_free():
    # Sweeps are independent simulators — sharding ONLY the sweep axis
    # must compile to zero cross-device traffic (parallel/mesh.py).
    cfg = Config(protocol="raft", n_nodes=1024, n_rounds=8, n_sweeps=8,
                 log_capacity=32, max_entries=24, max_active=8, seed=6,
                 drop_rate=0.01, churn_rate=0.001)
    colls = compiled_collectives(cfg, (8,))
    assert not colls, f"sweep-parallel round emitted collectives: {colls}"


def test_node_sharded_digest_matches_unsharded():
    # The census proves efficiency; this pins correctness of the very
    # config it censused (GSPMD partitioning is digest-neutral — and,
    # since the donation PR, buffer reuse across dispatches is too).
    base = simulator.run(CAPPED)
    sharded = simulator.run(CAPPED, mesh=make_mesh((2, 4)))
    assert base.digest == sharded.digest
