"""Async double-buffered checkpoint writer (network/ckpt_writer.py).

Contracts under test:

1. **Bit-identity** — async (the default) vs sync checkpointing produce
   the same final state for every engine AND byte-identical snapshot
   files at every rotation, including runs with a ragged tail chunk
   (snapshot bytes are a pure function of carry + meta since the zip
   timestamps were pinned, so equality is exact, not modulo mtime).
2. **Overlap accounting** — with the write step artificially slowed and
   the chunk compute slowed slightly more, the chunk loop's blocking
   time (``save_s``) stays strictly below the sync baseline's while
   ``save_hidden_s`` records the overlapped work. The injected delays
   dominate scheduler noise, so the ordering is deterministic — no
   wall-clock-flaky thresholds.
3. **Backpressure** — when writes are slower than two chunks of
   compute, the depth-1 queue blocks the third submit and the wait
   lands in the ``checkpoint_backpressure_s`` histogram.
4. **Error mirroring** — a writer-thread failure is recorded as a
   traced ``checkpoint_write_failed`` event plus the
   ``checkpoint_errors`` counter, then re-raised on the main thread at
   the next submit or the final drain barrier (never silently dropped).
5. **Crash-injection contract** — with a fault plan active,
   ``faults.on_chunk_end`` observes each chunk's snapshot durably
   renamed (the harness forces the drain barrier), so kill-after-chunk
   semantics survive the overlap.
6. **Grouped-sweep groundwork** — ``run(group_dir=...)`` writes the
   per-group subdirectory layout plus a completed-group manifest that
   round-trips (and rejects foreign configs/seeds).
"""
import dataclasses
import json
import time

import numpy as np
import pytest

from consensus_tpu.core.config import Config
from consensus_tpu.network import faults, runner, simulator
from consensus_tpu.obs import metrics as obs_metrics
from consensus_tpu.obs import trace as obs_trace

ADV = dict(drop_rate=0.1, partition_rate=0.05, churn_rate=0.05)

# scan_chunk=7 over 24 rounds → chunks 7+7+7+3: saves at r=7,14,21 and a
# ragged TAIL chunk after the last save (the acceptance criterion's
# "incl. scan_chunk tail chunks").
ENGINE_CFGS = {
    "raft": Config(protocol="raft", n_nodes=5, n_rounds=24, n_sweeps=2,
                   log_capacity=16, max_entries=8, scan_chunk=7, **ADV),
    "raft-sparse": Config(protocol="raft", n_nodes=16, max_active=4,
                          n_rounds=24, n_sweeps=2, log_capacity=16,
                          max_entries=8, scan_chunk=7, **ADV),
    "pbft": Config(protocol="pbft", f=1, n_nodes=4, n_rounds=24,
                   log_capacity=8, scan_chunk=7, **ADV),
    "pbft-bcast": Config(protocol="pbft", fault_model="bcast", f=2,
                         n_nodes=7, n_rounds=24, log_capacity=8,
                         scan_chunk=7, **ADV),
    "paxos": Config(protocol="paxos", n_nodes=7, n_rounds=24,
                    log_capacity=8, scan_chunk=7, **ADV),
    "dpos": Config(protocol="dpos", n_nodes=16, n_rounds=24,
                   log_capacity=32, n_candidates=8, n_producers=2,
                   epoch_len=8, scan_chunk=7, **ADV),
}

CFG = dataclasses.replace(ENGINE_CFGS["raft"], n_rounds=48, scan_chunk=8)


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


# --- 1. async-vs-sync bit-identity -------------------------------------------

@pytest.mark.parametrize("name", list(ENGINE_CFGS))
def test_async_equals_sync_bit_identical_per_engine(name, tmp_path):
    cfg = ENGINE_CFGS[name]
    eng = simulator.engine_def(cfg)
    ck_s, ck_a = tmp_path / "sync" / "ck.npz", tmp_path / "async" / "ck.npz"
    s_stats, a_stats = {}, {}
    out_s = runner.run(cfg, eng, checkpoint_path=ck_s, keep_checkpoints=4,
                       sync_checkpoints=True, stats=s_stats)
    out_a = runner.run(cfg, eng, checkpoint_path=ck_a, keep_checkpoints=4,
                       stats=a_stats)
    for k in out_s:
        np.testing.assert_array_equal(out_s[k], out_a[k], err_msg=k)

    # On-disk snapshot bytes: every rotation byte-identical. keep=4 and
    # 3 saves (r=7,14,21), so nothing rotated away.
    cands_s = runner.checkpoint_candidates(ck_s)
    cands_a = runner.checkpoint_candidates(ck_a)
    assert [p.name for p in cands_s] == [p.name for p in cands_a]
    assert len(cands_s) == 3
    for ps, pa in zip(cands_s, cands_a):
        assert ps.read_bytes() == pa.read_bytes(), (ps, pa)

    # Accounting shape: async hid work off-thread, sync hid none.
    aio, sio = a_stats["checkpoint_io"], s_stats["checkpoint_io"]
    assert aio["saves"] == sio["saves"] == 3
    assert aio["bytes_written"] == sio["bytes_written"]
    assert aio["save_hidden_s"] > 0 and aio["pull_s"] > 0 \
        and aio["write_s"] > 0
    assert sio["save_hidden_s"] == 0.0
    assert sio["pull_s"] > 0 and sio["write_s"] > 0


def test_async_digest_and_resume_bit_identical(tmp_path):
    """Final digest through the simulator front door, plus a resume
    from an async-written snapshot — the format really is unchanged."""
    base = simulator.run(CFG, warmup=False)
    ck = tmp_path / "ck.npz"
    res = simulator.run(CFG, warmup=False, checkpoint_path=str(ck),
                        resume=True)
    assert res.digest == base.digest
    assert res.extras["checkpoint_io"]["save_hidden_s"] > 0
    resumed = simulator.run(CFG, warmup=False, checkpoint_path=str(ck),
                            resume=True)
    assert resumed.digest == base.digest
    assert resumed.extras["checkpoint_io"]["loads"] == 1


def test_snapshot_bytes_deterministic_across_time(tmp_path):
    """The pinned-timestamp container: snapshot bytes are a pure
    function of carry + meta — the property the async-vs-sync byte
    comparison stands on. Asserted structurally (every zip member
    carries the pinned epoch, not the wall clock) plus byte equality of
    two saves, so no sleep across a 2-second DOS-mtime boundary is
    needed to prove it."""
    import zipfile

    import jax.numpy as jnp
    from consensus_tpu.engines import raft
    eng = raft.get_engine()
    seeds = jnp.asarray(runner.make_seeds(CFG))
    carry = runner._chunk_jit(CFG, eng, 8,
                              runner._init_jit(CFG, eng, seeds),
                              jnp.int32(0))
    a, b = tmp_path / "a.npz", tmp_path / "b.npz"
    runner.save_checkpoint(a, CFG, carry, 8)
    runner.save_checkpoint(b, CFG, carry, 8)
    assert a.read_bytes() == b.read_bytes()
    with zipfile.ZipFile(a) as zf:
        assert zf.namelist()[0] == "__meta__.npy"  # member order kept
        for info in zf.infolist():
            assert info.date_time == (1980, 1, 1, 0, 0, 0), info.filename


# --- 2. overlap: blocking strictly below the sync baseline -------------------

def _slowed(monkeypatch, write_delay, compute_delay):
    real_write = runner._write_snapshot
    real_chunk = runner._chunk_jit

    def slow_write(*a, **kw):
        time.sleep(write_delay)
        return real_write(*a, **kw)

    def slow_chunk(*a, **kw):
        time.sleep(compute_delay)
        return real_chunk(*a, **kw)

    monkeypatch.setattr(runner, "_write_snapshot", slow_write)
    monkeypatch.setattr(runner, "_chunk_jit", slow_chunk)


def test_async_blocking_strictly_below_sync_baseline(tmp_path, monkeypatch):
    """THE acceptance criterion: with the write step slowed by 25 ms and
    each chunk's compute slowed by 30 ms, the sync baseline must block
    the chunk loop >= 5 x 25 ms while the async pipeline hides every
    write behind the next chunk (blocking ~= enqueue epsilons). The
    injected delays make the ordering deterministic — this asserts
    async < sync, not any absolute wall-clock number."""
    eng = simulator.engine_def(CFG)
    base = runner.run(CFG, eng)  # compile before the slowdown
    _slowed(monkeypatch, write_delay=0.025, compute_delay=0.030)

    s_stats, a_stats = {}, {}
    out_s = runner.run(CFG, eng, checkpoint_path=tmp_path / "s.npz",
                       sync_checkpoints=True, stats=s_stats)
    out_a = runner.run(CFG, eng, checkpoint_path=tmp_path / "a.npz",
                       stats=a_stats)
    for k in base:
        np.testing.assert_array_equal(base[k], out_a[k], err_msg=k)
        np.testing.assert_array_equal(base[k], out_s[k], err_msg=k)

    sio, aio = s_stats["checkpoint_io"], a_stats["checkpoint_io"]
    assert sio["saves"] == aio["saves"] == 5  # 48 rounds / chunk 8
    assert sio["save_s"] >= 5 * 0.025          # sync pays every write
    assert aio["save_s"] < sio["save_s"]       # async strictly below
    assert aio["save_hidden_s"] >= 5 * 0.025   # ...because it hid them


def test_backpressure_blocks_and_is_observed(tmp_path, monkeypatch):
    """Depth-1 queue semantics: writes slower than two chunks of compute
    force the third submit to wait for the in-flight write; the wait is
    observed in checkpoint_backpressure_s and counted as blocking."""
    eng = simulator.engine_def(CFG)
    runner.run(CFG, eng)  # compile before the slowdown
    _slowed(monkeypatch, write_delay=0.05, compute_delay=0.0)
    obs_metrics.reset()
    stats: dict = {}
    runner.run(CFG, eng, checkpoint_path=tmp_path / "ck.npz", stats=stats)
    h = obs_metrics.snapshot()["checkpoint_backpressure_s"]
    assert h["count"] == 5                      # one observation per submit
    # With ~0 compute the pipeline degenerates to sequential writes:
    # at least the 3rd..5th submits must have genuinely blocked.
    assert h["sum"] >= 3 * 0.04
    assert stats["checkpoint_io"]["save_s"] >= 3 * 0.04


# --- 3. writer errors are mirrored, then re-raised ---------------------------

@pytest.mark.parametrize("n_rounds, surface", [(48, "next submit"),
                                               (16, "final drain")])
def test_writer_error_mirrored_and_reraised(tmp_path, monkeypatch, capsys,
                                            n_rounds, surface):
    cfg = dataclasses.replace(CFG, n_rounds=n_rounds)
    eng = simulator.engine_def(cfg)

    def boom(*a, **kw):
        raise OSError("disk full (injected)")

    monkeypatch.setattr(runner, "_write_snapshot", boom)
    obs_metrics.reset()
    trace_path = tmp_path / "t.jsonl"
    obs_trace.configure(str(trace_path))
    try:
        with pytest.raises(OSError, match="disk full"):
            # 48 rounds: error surfaces at the SECOND submit; 16 rounds
            # (single save): only the final drain barrier can raise it.
            runner.run(cfg, eng, checkpoint_path=tmp_path / "ck.npz")
    finally:
        obs_trace.close()
    assert obs_metrics.snapshot()["checkpoint_errors"]["value"] >= 1, surface
    recs = [json.loads(x) for x in trace_path.read_text().splitlines()[1:]]
    evs = [r for r in recs if r["type"] == "event"
           and r["name"] == "checkpoint_write_failed"]
    assert evs and "disk full" in evs[0]["attrs"]["error"]
    assert evs[0]["attrs"]["next_round"] == 8


def test_exception_in_chunk_loop_still_drains_writer(tmp_path, monkeypatch):
    """A main-loop failure must wait for the in-flight write (no
    background write may race a retry's resume) and must propagate the
    ORIGINAL error, not a writer state error."""
    eng = simulator.engine_def(CFG)
    runner.run(CFG, eng)  # compile first
    monkeypatch.setattr(runner, "_write_snapshot",
                        _delayed(runner._write_snapshot, 0.05))
    faults.install(transient_dispatches=[3])
    ck = tmp_path / "ck.npz"
    with pytest.raises(faults.InjectedTransientError):
        runner.run(CFG, eng, checkpoint_path=ck)
    # Both completed chunks' snapshots are durably renamed post-drain.
    assert runner.peek_checkpoint(ck, CFG) == 16


def _delayed(fn, delay):
    def wrapper(*a, **kw):
        time.sleep(delay)
        return fn(*a, **kw)
    return wrapper


# --- 4. crash-injection contract under the async writer ----------------------

def test_kill_hook_observes_durable_snapshot(tmp_path, monkeypatch):
    """With a fault plan active, by the time on_chunk_end fires the
    just-submitted snapshot is durably renamed (the harness forces the
    drain barrier) — kill_after_chunk keeps its pre-async meaning."""
    ck = tmp_path / "ck.npz"
    faults.install(kill_after_chunk=9999)  # plan active; kill never fires
    seen = []
    orig = faults.on_chunk_end

    def probe():
        seen.append(runner.peek_checkpoint(ck, CFG))
        orig()

    monkeypatch.setattr(faults, "on_chunk_end", probe)
    eng = simulator.engine_def(CFG)
    runner.run(CFG, eng, checkpoint_path=ck)
    # Saves at r=8..40; the final chunk (40→48) saves nothing, so the
    # last hook still sees 40.
    assert seen == [8, 16, 24, 32, 40, 40]


# --- 5. usage errors ---------------------------------------------------------

def test_sync_checkpoints_without_path_rejected():
    eng = simulator.engine_def(CFG)
    with pytest.raises(ValueError, match="sync_checkpoints"):
        runner.run(CFG, eng, sync_checkpoints=True)


def test_submit_after_close_rejected():
    from consensus_tpu.network.ckpt_writer import CheckpointWriter
    w = CheckpointWriter()
    w.close()
    w.close()  # idempotent
    with pytest.raises(RuntimeError, match="closed"):
        w.submit("x.npz", CFG, None, 8, seeds=np.zeros(2, np.uint32))


# --- 6. grouped-sweep resume groundwork --------------------------------------

GCFG = dataclasses.replace(ENGINE_CFGS["raft"], n_sweeps=4, sweep_chunk=3,
                           scan_chunk=8)


def test_group_dir_layout_manifest_and_bit_identity(tmp_path):
    eng = simulator.engine_def(GCFG)
    base = runner.run(dataclasses.replace(GCFG, sweep_chunk=0), eng)
    root = tmp_path / "groups"
    stats: dict = {}
    out = runner.run(GCFG, eng, group_dir=root, stats=stats)
    for k in base:
        np.testing.assert_array_equal(base[k], out[k], err_msg=k)
    # Layout: one subdirectory per group (4 sweeps / chunk 3 → 2 groups),
    # each holding its own rotation set, plus the manifest.
    assert runner.group_checkpoint_path(root, 0).exists()
    assert runner.group_checkpoint_path(root, 1).exists()
    assert runner.read_group_manifest(root, GCFG) == [0, 1]
    # Aggregated IO across groups: each group saved mid-run at r=8, 16
    # plus its FINAL snapshot at r=24 (the grouped-resume skip handle).
    assert stats["checkpoint_io"]["saves"] == 6
    assert stats["n_groups"] == 2 and stats["groups_skipped"] == 0
    # Foreign config or seed vector → not-my-manifest, like snapshots.
    assert runner.read_group_manifest(
        root, dataclasses.replace(GCFG, seed=GCFG.seed + 1)) is None
    assert runner.read_group_manifest(
        root, GCFG, seeds=np.asarray([7, 8, 9, 10], np.uint32)) is None
    # Each group's newest snapshot is its final carry (next_round ==
    # n_rounds), validating for ITS sub-config and seed slice.
    groups = runner._sweep_groups(GCFG)
    for gi, (sub, s) in enumerate(groups):
        assert runner.peek_checkpoint(
            runner.group_checkpoint_path(root, gi), sub, seeds=s) == 24


def test_group_dir_resume_skips_completed_and_resumes_mid_scan(tmp_path):
    """The grouped-resume contract end to end: a finished run resumes
    by LOADING every group (zero rounds executed); a doctored
    interrupted state — group 1's final snapshot gone, its r=16
    mid-run rotation left behind, manifest claiming only group 0 —
    skips group 0 and resumes group 1 mid-scan. Outputs bit-match the
    uninterrupted run in both cases."""
    eng = simulator.engine_def(GCFG)
    base = runner.run(dataclasses.replace(GCFG, sweep_chunk=0), eng)
    root = tmp_path / "groups"
    runner.run(GCFG, eng, group_dir=root)

    # Resume of a COMPLETE run: both groups skip via final snapshots.
    stats: dict = {}
    out = runner.run(GCFG, eng, group_dir=root, resume=True, stats=stats)
    for k in base:
        np.testing.assert_array_equal(base[k], out[k], err_msg=k)
    assert stats["groups_skipped"] == 2
    assert stats["group_start_rounds"] == [24, 24]
    assert stats["checkpoint_io"]["saves"] == 0  # nothing rewritten
    assert stats["checkpoint_io"]["loads"] == 2

    # Doctor an interrupted state: group 1 died after its r=16 save.
    g1 = runner.group_checkpoint_path(root, 1)
    g1.unlink()                                   # final (r=24) gone
    runner.rotation_path(g1, 1).rename(g1)        # r=16 mid-run -> latest
    meta, _ = runner._read_verified(g1)
    assert meta["next_round"] == 16
    groups = runner._sweep_groups(GCFG)
    runner.write_group_manifest(root, GCFG, runner.make_seeds(GCFG), [0],
                                len(groups))
    stats = {}
    out = runner.run(GCFG, eng, group_dir=root, resume=True, stats=stats)
    for k in base:
        np.testing.assert_array_equal(base[k], out[k], err_msg=k)
    assert stats["groups_skipped"] == 1
    assert stats["group_start_rounds"] == [24, 16]
    # The recovered run repaired the layout: manifest complete again,
    # group 1's final snapshot rewritten.
    assert runner.read_group_manifest(root, GCFG) == [0, 1]
    sub, s = groups[1]
    assert runner.peek_checkpoint(g1, sub, seeds=s) == 24


def test_group_dir_usage_errors(tmp_path):
    eng = simulator.engine_def(GCFG)
    with pytest.raises(ValueError, match="exclusive"):
        runner.run(GCFG, eng, group_dir=tmp_path / "g",
                   checkpoint_path=tmp_path / "ck.npz")
    with pytest.raises(ValueError, match="sweep_chunk"):
        runner.run(dataclasses.replace(GCFG, sweep_chunk=0), eng,
                   group_dir=tmp_path / "g")
    with pytest.raises(ValueError, match="final_checkpoint"):
        runner.run(dataclasses.replace(GCFG, sweep_chunk=0), eng,
                   final_checkpoint=True)


def test_checkpoint_with_sweep_chunk_points_to_group_dir(tmp_path):
    eng = simulator.engine_def(GCFG)
    with pytest.raises(ValueError, match="group_dir"):
        runner.run(GCFG, eng, checkpoint_path=tmp_path / "ck.npz")


# --- CLI integration ---------------------------------------------------------

def _cli_flags(ck=None, extra=()):
    from consensus_tpu import cli
    flags = ["--protocol", "raft", "--nodes", "5", "--rounds", "48",
             "--sweeps", "2", "--log-capacity", "16", "--max-entries", "8",
             "--scan-chunk", "8", "--drop-rate", "0.1",
             "--partition-rate", "0.05", "--churn-rate", "0.05",
             "--engine", "tpu", "--platform", "cpu"]
    if ck is not None:
        flags += ["--checkpoint", str(ck)]
    return cli, flags + list(extra)


def test_cli_sync_checkpoints_roundtrip_and_verbose(tmp_path, capsys):
    base = simulator.run(CFG, warmup=False)
    cli, flags = _cli_flags(tmp_path / "a.npz", ["-v"])
    assert cli.main(flags) == 0
    cap = capsys.readouterr()
    rep_async = json.loads(cap.out.strip().splitlines()[-1])
    assert rep_async["digest"] == base.digest
    assert "hidden" in cap.err and "blocking" in cap.err
    io = rep_async["checkpoint_io"]
    assert io["saves"] == 5 and io["save_hidden_s"] > 0

    cli2, flags2 = _cli_flags(tmp_path / "s.npz",
                              ["--sync-checkpoints", "-v"])
    assert cli2.main(flags2) == 0
    rep_sync = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rep_sync["digest"] == base.digest
    assert rep_sync["checkpoint_io"]["save_hidden_s"] == 0


def test_cli_sync_checkpoints_requires_checkpoint():
    cli, flags = _cli_flags(extra=["--sync-checkpoints"])
    with pytest.raises(SystemExit):
        cli.main(flags)


def test_cli_rejects_sync_checkpoints_on_cpu_engine():
    from consensus_tpu import cli
    with pytest.raises(SystemExit):
        cli.main(["--protocol", "raft", "--engine", "cpu",
                  "--sync-checkpoints"])
