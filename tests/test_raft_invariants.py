"""Raft safety invariants over whole runs (SPEC §3; Raft Fig. 3), checked on
the TPU engine under adversarial seeds (SURVEY.md §4.2)."""
import dataclasses

import numpy as np
import pytest

from consensus_tpu import Config
from consensus_tpu.network import simulator

from helpers import run_cached

CFGS = [
    Config(protocol="raft", n_nodes=5, n_rounds=96, log_capacity=128,
           max_entries=100, n_sweeps=6, seed=101,
           drop_rate=0.3, partition_rate=0.2, churn_rate=0.1),
    Config(protocol="raft", n_nodes=9, n_rounds=96, log_capacity=128,
           max_entries=100, n_sweeps=4, seed=202,
           drop_rate=0.4, churn_rate=0.2),
]


@pytest.mark.parametrize("cfg", CFGS)
def test_state_machine_safety(cfg):
    """All nodes' committed prefixes agree (same (term, val) at same index)."""
    res = run_cached(cfg)
    for b in range(cfg.n_sweeps):
        counts = res.counts[b]
        for i in range(cfg.n_nodes):
            for j in range(i + 1, cfg.n_nodes):
                c = int(min(counts[i], counts[j]))
                np.testing.assert_array_equal(
                    res.rec_a[b, i, :c], res.rec_a[b, j, :c],
                    err_msg=f"sweep {b}: committed term divergence {i}/{j}")
                np.testing.assert_array_equal(
                    res.rec_b[b, i, :c], res.rec_b[b, j, :c],
                    err_msg=f"sweep {b}: committed value divergence {i}/{j}")


@pytest.mark.parametrize("cfg", CFGS)
def test_log_matching_final(cfg):
    """Entries with the same index and term are identical across logs
    (Raft Log Matching, checked on final logs)."""
    from consensus_tpu.engines.raft import raft_run
    out = raft_run(cfg)
    lt, lv = out["log_term"], out["log_val"]
    for b in range(cfg.n_sweeps):
        for i in range(cfg.n_nodes):
            for j in range(i + 1, cfg.n_nodes):
                same = (lt[b, i] == lt[b, j]) & (lt[b, i] != 0)
                np.testing.assert_array_equal(
                    lv[b, i][same], lv[b, j][same],
                    err_msg=f"sweep {b}: log-matching violation {i}/{j}")


def test_partitioned_minority_cannot_commit():
    """With a permanent-ish partition pattern, committed entries never exceed
    what a majority could replicate: commit counts stay consistent (safety
    already checked above); here: no node's commit exceeds max_entries and
    commit <= log_len always."""
    cfg = Config(protocol="raft", n_nodes=5, n_rounds=96, log_capacity=128,
                 max_entries=50, n_sweeps=4, seed=303, partition_rate=0.8)
    from consensus_tpu.engines.raft import raft_run
    out = raft_run(cfg)
    assert (out["commit"] <= 50).all()
    lens = (out["log_term"] != 0).sum(axis=2)
    assert (out["commit"] <= lens).all()
