"""Raft safety invariants over whole runs (SPEC §3; Raft Fig. 3), checked on
the TPU engine under adversarial seeds (SURVEY.md §4.2): State-Machine
Safety and Log Matching on final states; Election Safety and Leader
Completeness on per-round traces (they are statements about *when* leaders
exist and what they held at election time). The companion demonstration
that Election Safety *fails* under the §3c equivocate adversary lives in
tests/test_raft_byz.py (the invariants here assume honest nodes)."""
import dataclasses

import numpy as np
import pytest

from consensus_tpu import Config
from consensus_tpu.network import simulator

from helpers import committed_prefixes_agree, run_cached, trace_raft_rounds

CFGS = [
    Config(protocol="raft", n_nodes=5, n_rounds=96, log_capacity=128,
           max_entries=100, n_sweeps=6, seed=101,
           drop_rate=0.3, partition_rate=0.2, churn_rate=0.1),
    Config(protocol="raft", n_nodes=9, n_rounds=96, log_capacity=128,
           max_entries=100, n_sweeps=4, seed=202,
           drop_rate=0.4, churn_rate=0.2),
]


@pytest.mark.parametrize("cfg", CFGS)
def test_state_machine_safety(cfg):
    """All nodes' committed prefixes agree (same (term, val) at same index)."""
    res = run_cached(cfg)
    for b in range(cfg.n_sweeps):
        assert committed_prefixes_agree(res, list(range(cfg.n_nodes)), b), \
            f"sweep {b}: committed prefix divergence"


@pytest.mark.parametrize("cfg", CFGS)
def test_log_matching_final(cfg):
    """Entries with the same index and term are identical across logs
    (Raft Log Matching, checked on final logs)."""
    from consensus_tpu.engines.raft import raft_run
    out = raft_run(cfg)
    lt, lv = out["log_term"], out["log_val"]
    for b in range(cfg.n_sweeps):
        for i in range(cfg.n_nodes):
            for j in range(i + 1, cfg.n_nodes):
                same = (lt[b, i] == lt[b, j]) & (lt[b, i] != 0)
                np.testing.assert_array_equal(
                    lv[b, i][same], lv[b, j][same],
                    err_msg=f"sweep {b}: log-matching violation {i}/{j}")


@pytest.mark.parametrize("cfg", CFGS)
def test_election_safety(cfg):
    """At most one leader per term (Raft Fig. 3, Election Safety), tracked
    over every round of every sweep — precisely the invariant the §3c
    equivocate adversary breaks (and honest runs must never)."""
    tr = trace_raft_rounds(cfg, None)
    for b in range(cfg.n_sweeps):
        winners: dict[int, set[int]] = {}
        for r in range(cfg.n_rounds):
            for i in np.nonzero(tr["role"][r, b] == 2)[0]:
                winners.setdefault(int(tr["term"][r, b, i]), set()).add(int(i))
        multi = {t: w for t, w in winners.items() if len(w) > 1}
        assert not multi, f"sweep {b}: two leaders in a term: {multi}"


@pytest.mark.parametrize("cfg", CFGS)
def test_leader_completeness(cfg):
    """Every entry committed before round r is present in the log of every
    node that is leader at round r (Raft Fig. 3, Leader Completeness) —
    checked against the deepest committed prefix observed so far, whose
    content is pinned by State-Machine Safety (asserted above)."""
    tr = trace_raft_rounds(cfg, None)
    role, commit = tr["role"], tr["commit"]
    lt, lv = tr["log_term"], tr["log_val"]
    for b in range(cfg.n_sweeps):
        cmax = 0                   # deepest commit at any node so far
        pref_t = pref_v = None     # its content, from the committing node
        for r in range(cfg.n_rounds):
            if cmax > 0:
                for i in np.nonzero(role[r, b] == 2)[0]:
                    np.testing.assert_array_equal(
                        lt[r, b, i, :cmax], pref_t,
                        err_msg=f"sweep {b} round {r}: leader {i} missing "
                                "committed terms")
                    np.testing.assert_array_equal(
                        lv[r, b, i, :cmax], pref_v,
                        err_msg=f"sweep {b} round {r}: leader {i} missing "
                                "committed values")
            deep = int(commit[r, b].max())
            if deep > cmax:
                cmax = deep
                j = int(commit[r, b].argmax())
                pref_t = lt[r, b, j, :cmax].copy()
                pref_v = lv[r, b, j, :cmax].copy()


# --- safety while nodes churn through crash/recover cycles (SPEC §6c) -------
#
# Election Safety and Log Matching are checked on the LIVE set: a node
# frozen mid-crash legitimately still shows its pre-crash role/log, but
# among reachable nodes the invariants must hold exactly as in the
# honest runs above — voted_for and the log are §6c-durable, so a
# recovered node can neither double-vote in a term it already voted in
# nor resurrect truncated entries.

CRASH_CFGS = [
    Config(protocol="raft", n_nodes=5, n_rounds=96, log_capacity=128,
           max_entries=100, n_sweeps=4, seed=404,
           drop_rate=0.2, churn_rate=0.1, crash_prob=0.15, recover_prob=0.3),
    Config(protocol="raft", n_nodes=9, n_rounds=96, log_capacity=128,
           max_entries=100, n_sweeps=3, seed=505, drop_rate=0.3,
           partition_rate=0.1, crash_prob=0.2, recover_prob=0.25,
           max_crashed=4),
]


@pytest.mark.parametrize("cfg", CRASH_CFGS)
def test_election_safety_live_set_under_crashes(cfg):
    """At most one LIVE leader per term, every round, while nodes crash
    and recover — the invariant a volatile voted_for would break (a
    rejoining node that forgot its vote could elect a second leader)."""
    tr = trace_raft_rounds(cfg, None)
    crashed_rounds = tr["down"].any(axis=(0, 2))
    assert crashed_rounds.all(), "adversary never fired — test is vacuous"
    for b in range(cfg.n_sweeps):
        winners: dict[int, set[int]] = {}
        for r in range(cfg.n_rounds):
            live_lead = (tr["role"][r, b] == 2) & ~tr["down"][r, b]
            for i in np.nonzero(live_lead)[0]:
                winners.setdefault(int(tr["term"][r, b, i]), set()).add(int(i))
        multi = {t: w for t, w in winners.items() if len(w) > 1}
        assert not multi, f"sweep {b}: two live leaders in a term: {multi}"


@pytest.mark.parametrize("cfg", CRASH_CFGS)
def test_log_matching_live_set_under_crashes(cfg):
    """Log Matching over every round's live set: entries with the same
    (index, term) are identical across every pair of reachable logs,
    sampled at rounds 1/4, 1/2, 3/4 and the final round."""
    tr = trace_raft_rounds(cfg, None)
    for b in range(cfg.n_sweeps):
        for r in {cfg.n_rounds // 4, cfg.n_rounds // 2,
                  3 * cfg.n_rounds // 4, cfg.n_rounds - 1}:
            live = np.nonzero(~tr["down"][r, b])[0]
            lt, lv = tr["log_term"][r, b], tr["log_val"][r, b]
            for a, i in enumerate(live):
                for j in live[a + 1:]:
                    same = (lt[i] == lt[j]) & (lt[i] != 0)
                    np.testing.assert_array_equal(
                        lv[i][same], lv[j][same],
                        err_msg=f"sweep {b} round {r}: log-matching "
                                f"violation {i}/{j}")


@pytest.mark.parametrize("cfg", CRASH_CFGS)
def test_state_machine_safety_under_crashes(cfg):
    """Committed prefixes agree across ALL nodes — including frozen
    ones, whose prefix is a (durable) earlier commit of the same log."""
    res = run_cached(cfg)
    for b in range(cfg.n_sweeps):
        assert committed_prefixes_agree(res, list(range(cfg.n_nodes)), b), \
            f"sweep {b}: committed prefix divergence under crashes"


def test_partitioned_minority_cannot_commit():
    """With a permanent-ish partition pattern, committed entries never exceed
    what a majority could replicate: commit counts stay consistent (safety
    already checked above); here: no node's commit exceeds max_entries and
    commit <= log_len always."""
    cfg = Config(protocol="raft", n_nodes=5, n_rounds=96, log_capacity=128,
                 max_entries=50, n_sweeps=4, seed=303, partition_rate=0.8)
    from consensus_tpu.engines.raft import raft_run
    out = raft_run(cfg)
    assert (out["commit"] <= 50).all()
    lens = (out["log_term"] != 0).sum(axis=2)
    assert (out["commit"] <= lens).all()
