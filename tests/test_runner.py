"""Runner features: mesh sharding, blocked scan, checkpoint/resume
(SURVEY.md §4.4 — same code path as a real v5e-8, on the virtual CPU mesh),
plus the driver entry points in __graft_entry__.py.

Everything must be *bit-identical* to the plain single-device run: the
decided log is the observable, and sharding/chunking/resume are execution
strategies, not semantic changes.
"""
import pathlib
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensus_tpu.core.config import Config
from consensus_tpu.engines import dpos, paxos, pbft, raft
from consensus_tpu.network import runner
from consensus_tpu.parallel.mesh import make_mesh

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

ADV = dict(drop_rate=0.1, partition_rate=0.05, churn_rate=0.05)

CFGS = {
    "raft": Config(protocol="raft", n_nodes=8, n_rounds=48, n_sweeps=4,
                   log_capacity=16, max_entries=8, **ADV),
    "pbft": Config(protocol="pbft", f=1, n_nodes=4, n_rounds=24, n_sweeps=4,
                   log_capacity=8, **ADV),
    "paxos": Config(protocol="paxos", n_nodes=8, n_rounds=24, n_sweeps=4,
                    log_capacity=8, **ADV),
    "dpos": Config(protocol="dpos", n_nodes=16, n_rounds=32, n_sweeps=4,
                   log_capacity=64, n_candidates=8, n_producers=2,
                   epoch_len=8, **ADV),
}
RUNS = {"raft": raft.raft_run, "pbft": pbft.pbft_run,
        "paxos": paxos.paxos_run, "dpos": dpos.dpos_run}


def _assert_same(a: dict, b: dict) -> None:
    assert a.keys() == b.keys()
    for k in a:
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)


@pytest.mark.parametrize("proto", list(CFGS))
def test_sharded_equals_unsharded(proto):
    cfg = CFGS[proto]
    base = RUNS[proto](cfg)
    mesh = make_mesh((2, 4) if cfg.n_nodes % 4 == 0 else (2, 2))
    _assert_same(base, RUNS[proto](cfg, mesh=mesh))


@pytest.mark.parametrize("proto", ["raft", "paxos"])
def test_sweep_only_mesh_via_config(proto):
    cfg = CFGS[proto]
    base = RUNS[proto](cfg)
    import dataclasses
    cfg8 = dataclasses.replace(cfg, mesh_shape=(4,))
    _assert_same(base, RUNS[proto](cfg8))


@pytest.mark.parametrize("proto", list(CFGS))
def test_chunked_scan_equals_plain(proto):
    import dataclasses
    cfg = CFGS[proto]
    base = RUNS[proto](cfg)
    # chunk size that doesn't divide n_rounds → exercises the ragged tail
    cfgc = dataclasses.replace(cfg, scan_chunk=7)
    _assert_same(base, RUNS[proto](cfgc))


@pytest.mark.parametrize("proto", list(CFGS))
@pytest.mark.parametrize("chunk", [1, 3])
def test_sweep_chunk_equals_one_program(proto, chunk):
    """Grouped-sweep execution (chunk=3 exercises the ragged 4=3+1 tail)
    is an execution strategy, not a semantic change: per-sweep seeds are
    position-based, so every sweep's trajectory is bit-identical."""
    import dataclasses
    cfg = CFGS[proto]
    base = RUNS[proto](cfg)
    cfgs = dataclasses.replace(cfg, sweep_chunk=chunk)
    _assert_same(base, RUNS[proto](cfgs))


def test_sweep_chunk_rejects_checkpoint(tmp_path):
    import dataclasses
    cfg = dataclasses.replace(CFGS["raft"], sweep_chunk=2)
    with pytest.raises(ValueError, match="sweep_chunk"):
        runner.run(cfg, raft.get_engine(),
                   checkpoint_path=tmp_path / "ck.npz")


def test_sweep_chunk_ragged_tail_unshardable_fails_fast():
    """4 sweeps grouped by 3 → tail of 1; a 2-wide sweep mesh axis can't
    shard it. Must raise before any group runs, not mid-run."""
    import dataclasses
    cfg = dataclasses.replace(CFGS["raft"], sweep_chunk=3, mesh_shape=(2, 2))
    with pytest.raises(ValueError, match="divisible"):
        runner.run(cfg, raft.get_engine())


def test_sweep_chunk_honors_explicit_seeds():
    cfg = CFGS["raft"]
    eng = raft.get_engine()
    seeds = np.asarray([17, 3, 29, 11], np.uint32)
    base = runner.run(cfg, eng, seeds=seeds)
    import dataclasses
    grouped = runner.run(dataclasses.replace(cfg, sweep_chunk=3), eng,
                         seeds=seeds)
    _assert_same(base, grouped)


def test_explicit_seeds_wrong_length_rejected():
    import dataclasses
    eng = raft.get_engine()
    short = np.asarray([1, 2], np.uint32)  # cfg has n_sweeps=4
    with pytest.raises(ValueError, match="seeds"):
        runner.run(CFGS["raft"], eng, seeds=short)
    with pytest.raises(ValueError, match="seeds"):
        runner.run(dataclasses.replace(CFGS["raft"], sweep_chunk=2), eng,
                   seeds=short)


def test_checkpoint_from_older_schema_still_resumes(tmp_path):
    """A snapshot written before a Config field existed must compare at
    that field's default, not be silently invalidated (and restart from
    round 0) by a key-for-key dict mismatch."""
    import dataclasses, json
    cfg = dataclasses.replace(CFGS["raft"], scan_chunk=16)
    eng = raft.get_engine()
    seeds = jnp.asarray(runner.make_seeds(cfg))
    carry = runner._init_jit(cfg, eng, seeds)
    carry = runner._chunk_jit(cfg, eng, 16, carry, jnp.int32(0))
    path = tmp_path / "ck.npz"
    runner.save_checkpoint(path, cfg, carry, 16)

    # Rewrite the snapshot's meta with sweep_chunk deleted, as a file
    # written by the pre-sweep_chunk schema would have it (that era also
    # predates the seeds record and the integrity manifest).
    with np.load(path) as z:
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
        meta = json.loads(bytes(z["__meta__"]).decode())
    del meta["config"]["sweep_chunk"]
    del meta["seeds"]  # pre-recorded-seeds era: implies make_seeds(cfg)
    meta.pop("integrity", None)
    np.savez(path, __meta__=np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8), **arrays)

    loaded = runner.load_checkpoint(path, cfg, eng)
    assert loaded is not None and loaded[1] == 16
    resumed = runner.run(cfg, eng, checkpoint_path=path, resume=True)
    _assert_same(RUNS["raft"](cfg), resumed)


def test_resume_under_different_seeds_is_a_mismatch(tmp_path):
    """A snapshot's carry belongs to the seed vector that produced it;
    resuming under different explicit seeds must restart, not continue
    the old trajectories mislabeled as the new ones."""
    import dataclasses
    cfg = dataclasses.replace(CFGS["raft"], scan_chunk=16)
    eng = raft.get_engine()
    seeds_a = np.asarray([7, 8, 9, 10], np.uint32)
    seeds_b = np.asarray([70, 80, 90, 100], np.uint32)
    path = tmp_path / "ck.npz"

    carry = runner._init_jit(cfg, eng, jnp.asarray(seeds_a))
    carry = runner._chunk_jit(cfg, eng, 16, carry, jnp.int32(0))
    runner.save_checkpoint(path, cfg, carry, 16, seeds=seeds_a)

    assert runner.load_checkpoint(path, cfg, eng, seeds=seeds_a) is not None
    assert runner.load_checkpoint(path, cfg, eng, seeds=seeds_b) is None
    # default-seed caller: also a mismatch with this explicit-seed file
    assert runner.load_checkpoint(path, cfg, eng) is None
    resumed = runner.run(cfg, eng, checkpoint_path=path, resume=True,
                         seeds=seeds_b)
    _assert_same(runner.run(cfg, eng, seeds=seeds_b), resumed)


def test_checkpoint_from_newer_schema_rejected(tmp_path):
    """A snapshot whose config carries a key the current schema doesn't
    know encodes semantics we can't represent — reject (restart), don't
    resume it or crash on it."""
    import dataclasses, json
    cfg = dataclasses.replace(CFGS["raft"], scan_chunk=16)
    eng = raft.get_engine()
    seeds = jnp.asarray(runner.make_seeds(cfg))
    carry = runner._init_jit(cfg, eng, seeds)
    carry = runner._chunk_jit(cfg, eng, 16, carry, jnp.int32(0))
    path = tmp_path / "ck.npz"
    runner.save_checkpoint(path, cfg, carry, 16)

    with np.load(path) as z:
        arrays = {k: z[k] for k in z.files if k != "__meta__"}
        meta = json.loads(bytes(z["__meta__"]).decode())
    # A foreign writer would have recorded its own manifest over its own
    # meta; strip ours so the *schema* rejection path is what's tested,
    # not the checksum one (tests/test_resilience.py covers checksums).
    meta.pop("integrity", None)
    meta["config"]["future_adversary_mode"] = 3
    np.savez(path, __meta__=np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8), **arrays)
    assert runner.load_checkpoint(path, cfg, eng) is None

    # An invalid-under-current-validation saved config is likewise a
    # mismatch (None), not an uncaught ValueError.
    del meta["config"]["future_adversary_mode"]
    meta["config"]["t_max"] = meta["config"]["t_min"]
    np.savez(path, __meta__=np.frombuffer(
        json.dumps(meta).encode(), dtype=np.uint8), **arrays)
    assert runner.load_checkpoint(path, cfg, eng) is None


def test_checkpoint_resume_bit_identical(tmp_path):
    import dataclasses
    cfg = dataclasses.replace(CFGS["raft"], scan_chunk=16)
    base = RUNS["raft"](cfg)

    # Interrupt after one chunk: run 16 rounds by hand, save, resume.
    eng = raft.get_engine()
    seeds = jnp.asarray(runner.make_seeds(cfg))
    carry = runner._init_jit(cfg, eng, seeds)
    carry = runner._chunk_jit(cfg, eng, 16, carry, jnp.int32(0))
    ckpt = tmp_path / "raft.ckpt.npz"
    runner.save_checkpoint(ckpt, cfg, carry, 16)

    resumed = raft.raft_run(cfg, checkpoint_path=ckpt, resume=True)
    _assert_same(base, resumed)


def test_checkpoint_from_wider_dtype_resumes_bit_identical(tmp_path):
    """A checkpoint written before a state field's storage dtype was
    narrowed (raft match/next i32 -> u8, round 5) must still resume:
    load_checkpoint casts leaves to the current init-template dtypes.
    Simulated by widening every saved leaf to its numpy default width."""
    import dataclasses

    import numpy as np
    cfg = dataclasses.replace(CFGS["raft"], scan_chunk=16)
    base = RUNS["raft"](cfg)

    eng = raft.get_engine()
    seeds = jnp.asarray(runner.make_seeds(cfg))
    carry = runner._init_jit(cfg, eng, seeds)
    carry = runner._chunk_jit(cfg, eng, 16, carry, jnp.int32(0))
    ckpt = tmp_path / "raft.ckpt.npz"
    runner.save_checkpoint(ckpt, cfg, carry, 16)

    import json
    with np.load(ckpt) as z:
        widened = {k: (z[k] if k == "__meta__"
                       else np.asarray(z[k], dtype=np.int64)
                       if np.issubdtype(z[k].dtype, np.integer) else z[k])
                   for k in z.files}
    # A wide-dtype-era writer predates the integrity manifest; strip it
    # (its leaf CRCs describe the narrow bytes) so the dtype-cast path
    # is what's exercised, not checksum rejection.
    meta = json.loads(bytes(widened["__meta__"]).decode())
    meta.pop("integrity", None)
    widened["__meta__"] = np.frombuffer(json.dumps(meta).encode(),
                                        dtype=np.uint8)
    np.savez(ckpt, **widened)

    resumed = raft.raft_run(cfg, checkpoint_path=ckpt, resume=True)
    _assert_same(base, resumed)


def test_checkpoint_config_mismatch_is_ignored(tmp_path):
    import dataclasses
    cfg = dataclasses.replace(CFGS["raft"], scan_chunk=16)
    eng = raft.get_engine()
    seeds = jnp.asarray(runner.make_seeds(cfg))
    carry = runner._init_jit(cfg, eng, seeds)
    ckpt = tmp_path / "raft.ckpt.npz"
    runner.save_checkpoint(ckpt, cfg, carry, 16)

    other = dataclasses.replace(cfg, seed=cfg.seed + 1)
    assert runner.load_checkpoint(ckpt, other, eng) is None
    # A resume request against a mismatched checkpoint falls back to a
    # fresh run — identical to never having checkpointed.
    _assert_same(RUNS["raft"](other),
                 raft.raft_run(other, checkpoint_path=ckpt, resume=True))


def test_resume_reports_executed_rounds_only(tmp_path):
    """A resumed run's stats (and the simulator's steps/sec) must count
    only the rounds it actually executed (ADVICE r1 #2)."""
    import dataclasses
    cfg = dataclasses.replace(CFGS["raft"], scan_chunk=16)
    eng = raft.get_engine()
    seeds = jnp.asarray(runner.make_seeds(cfg))
    carry = runner._init_jit(cfg, eng, seeds)
    carry = runner._chunk_jit(cfg, eng, 16, carry, jnp.int32(0))
    # Separate files: a resumed run overwrites its checkpoint as it
    # advances, which would move the second resume's start round.
    ckpt = tmp_path / "raft.ckpt.npz"
    ckpt2 = tmp_path / "raft2.ckpt.npz"
    runner.save_checkpoint(ckpt, cfg, carry, 16)
    runner.save_checkpoint(ckpt2, cfg, carry, 16)

    stats = {}
    runner.run(cfg, eng, checkpoint_path=ckpt, resume=True, stats=stats)
    assert stats["start_round"] == 16
    assert stats["executed_rounds"] == cfg.n_rounds - 16
    # A checkpointing run also accounts its IO (docs/OBSERVABILITY.md):
    # this resume loaded one snapshot and saved at r=32 (not after the
    # final chunk).
    assert stats["checkpoint_io"]["loads"] == 1
    assert stats["checkpoint_io"]["saves"] == 1
    assert stats["checkpoint_io"]["bytes_read"] > 0

    from consensus_tpu.network import simulator
    res = simulator.run(cfg, checkpoint_path=str(ckpt2), resume=True)
    assert res.node_round_steps == \
        cfg.n_sweeps * cfg.n_nodes * (cfg.n_rounds - 16)
    assert res.timing_includes_compile


def test_engine_kw_rejected_on_cpu_engine():
    """TPU-only run options must not be silently ignored (ADVICE r1 #3)."""
    import dataclasses

    from consensus_tpu.network import simulator
    cfg = dataclasses.replace(CFGS["raft"], engine="cpu")
    with pytest.raises(ValueError, match="only apply to the tpu engine"):
        simulator.run(cfg, checkpoint_path="/tmp/nope.npz", resume=True)


def test_mesh_divisibility_rejected():
    import dataclasses
    cfg = dataclasses.replace(CFGS["raft"], n_sweeps=3)
    with pytest.raises(ValueError, match="not divisible"):
        raft.raft_run(cfg, mesh=make_mesh((2, 1)))


def test_graft_entry_compiles():
    import __graft_entry__ as ge
    fn, args = ge.entry()
    out = jax.jit(fn)(*args)
    assert out.term.shape == args[0].term.shape


def test_graft_dryrun_multichip():
    import __graft_entry__ as ge
    ge.dryrun_multichip(8)
