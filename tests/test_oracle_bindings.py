"""Cross-language RNG parity: C++ threefry == numpy threefry."""
import numpy as np

from consensus_tpu.core import rng
from consensus_tpu.oracle import bindings


def test_threefry_cpp_matches_numpy():
    r = np.random.RandomState(7)
    for _ in range(50):
        seed = int(r.randint(0, 2**63, dtype=np.int64))
        stream = rng.STREAM_DELIVER if r.rand() < 0.5 else rng.STREAM_TIMEOUT
        ctx, c0, c1 = (int(x) for x in r.randint(0, 2**32, size=3, dtype=np.uint32))
        a = bindings.random_u32(seed, int(stream), ctx, c0, c1)
        b = int(rng.random_u32_np(seed, stream, ctx, c0, c1))
        assert a == b


def test_delivery_mixer_cpp_matches_numpy():
    r = np.random.RandomState(11)
    for _ in range(50):
        seed = int(r.randint(0, 2**63, dtype=np.int64))
        rr, i, j = (int(x) for x in r.randint(0, 2**32, size=3, dtype=np.uint32))
        a = bindings.delivery_u32(seed, rr, i, j)
        b = int(rng.delivery_u32_np(seed, rr, i, j))
        assert a == b
