"""Observatory layer 1: the compiled cost model (tools/costmodel).

Mirrors tests/test_hlocheck.py's pattern for the sibling artifact set:

  1. CLEAN REPO — every hlocheck-registered target has a committed,
     schema-valid cost card, and (same toolchain) what this compiler
     lowers today matches it;
  2. SEMANTICS — a card's cost/roofline blocks are internally
     consistent, the collective census reads off the committed mesh
     fingerprints at the 4-byte dtype bound, drift is detected
     field-by-field;
  3. SCALING — the 500k/1M node-sharded projection covers the declared
     grid, scales linearly, and answers the 1M-node HBM-fit question.
"""
import copy
import json
import os
import pathlib
import sys

import pytest

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from tools import validate_trace  # noqa: E402
from tools.costmodel import model  # noqa: E402
from tools.costmodel.__main__ import run_checks  # noqa: E402
from tools.hlocheck import registry  # noqa: E402

TARGET_NAMES = {t.name for t in registry.targets()}


# --- 1. clean repo -----------------------------------------------------------

def test_every_registered_target_has_a_committed_card():
    committed = {p.stem for p in model.COSTCARD_DIR.glob("*.json")}
    assert committed == TARGET_NAMES, (
        f"cost cards and hlocheck registry drifted: missing cards "
        f"{sorted(TARGET_NAMES - committed)}, orphaned cards "
        f"{sorted(committed - TARGET_NAMES)} — run "
        f"`python -m tools.costmodel --update`")


def test_committed_cards_validate_against_schema():
    for name in sorted(TARGET_NAMES):
        errs = validate_trace.validate_costcard(model.path_for(name))
        assert not errs, errs


@pytest.mark.skipif(
    os.environ.get("CONSENSUS_COST_LAYER_RAN") == "1",
    reason="the check.py costcheck layer already ran the full gate in "
           "this invocation (tools/check.py sets the env var)")
def test_costcheck_gate_is_clean():
    assert run_checks() == 0


# --- 2. card semantics -------------------------------------------------------

def _cheap_card():
    return model.build_card(registry.target("pbft-1k-dense"))


def test_card_internal_consistency():
    card = _cheap_card()
    assert tuple(card) == model.CARD_FIELDS
    c, roof = card["cost"], card["roofline"]
    assert c["flops_per_round"] > 0 and c["bytes_per_round"] > 0
    assert c["arithmetic_intensity"] == pytest.approx(
        c["flops_per_round"] / c["bytes_per_round"])
    cfg = registry.target("pbft-1k-dense").cfg
    assert c["steps_per_round"] == cfg.n_sweeps * cfg.n_nodes
    assert roof["predicted_steps_per_sec"] == pytest.approx(
        c["steps_per_round"] / roof["predicted_round_s"])
    # Integer VPU kernels sit far under the bf16 MXU peak: every
    # registered config must be bandwidth-bound or the model is wrong.
    assert roof["bound"] == "bandwidth"


def test_card_matches_committed_on_same_toolchain():
    committed = model.load("pbft-1k-dense")
    assert committed is not None
    if not model.same_toolchain(committed):
        pytest.skip("different jax/jaxlib than the committed card "
                    "(cross-toolchain drift only warns, like "
                    "fingerprints)")
    assert model.diff(committed, _cheap_card()) == []


def test_diff_detects_field_level_drift():
    card = model.load("raft-100k")
    tampered = copy.deepcopy(card)
    tampered["cost"]["bytes_per_round"] *= 2
    tampered["roofline"]["bound"] = "compute"
    lines = model.diff(card, tampered)
    assert any("cost.bytes_per_round" in ln for ln in lines)
    assert any("roofline.bound" in ln for ln in lines)
    assert model.diff(card, copy.deepcopy(card)) == []


def test_collective_census_reads_fingerprints_at_dtype_bound():
    card = model.load("raft-100k")
    census = card["collectives"]["node2x4"]["collectives"]
    assert "all-reduce" in census  # the quorum psum crosses the mesh
    for op, c in census.items():
        assert c["max_bytes"] == c["max_elems"] * model.MAX_ELEM_BYTES, op
    # Sweep-only meshes are collective-free by contract.
    assert card["collectives"]["sweep8"]["collectives"] == {}


def test_fsweep_card_counts_real_nodes_only():
    card = model.load("pbft-100k-bcast-fsweep")
    tgt = registry.target("pbft-100k-bcast-fsweep")
    want = tgt.cfg.n_sweeps * sum(3 * f + 1 for f in tgt.fsweep)
    assert card["cost"]["steps_per_round"] == want


# --- 3. scaling projection ---------------------------------------------------

def test_scale_rows_cover_grid_and_scale_linearly():
    rows = model.scale_rows()
    keys = {(r["name"], r["n_nodes"], r["devices"]) for r in rows}
    assert keys == {(n, N, d) for n in model.SCALE_TARGETS
                    for N in model.SCALE_NS for d in model.SCALE_DEVICES}
    by = {(r["name"], r["n_nodes"], r["devices"]): r for r in rows}
    r100, r1m = by[("raft-100k", 100_000, 1)], by[("raft-100k",
                                                   1_000_000, 1)]
    # Bandwidth-bound O(N) rounds: per-device bytes scale ~linearly and
    # steps/s is N-invariant at fixed D.
    assert r1m["bytes_per_round_per_device"] == pytest.approx(
        10 * r100["bytes_per_round_per_device"], rel=0.01)
    assert r1m["predicted_steps_per_sec"] == pytest.approx(
        r100["predicted_steps_per_sec"], rel=0.01)
    # The ROADMAP question this table answers: a 1M-node raft-sparse
    # carry fits ONE chip's HBM — the mesh buys wall time, not
    # feasibility.
    assert r1m["fits_hbm"] and r1m["carry_bytes"] < 16 * 1024**3
    # Sharding helps: 8 devices beat 1 at every N.
    for name in model.SCALE_TARGETS:
        for N in model.SCALE_NS:
            assert (by[(name, N, 8)]["predicted_steps_per_sec"]
                    > by[(name, N, 1)]["predicted_steps_per_sec"])


def test_scale_markdown_renders_every_row():
    rows = model.scale_rows()
    md = model.scale_markdown(rows)
    assert md.count("\n") == len(rows) + 1  # header + divider + rows


def test_committed_scale_table_matches_cards():
    # Drift gate for the docs/SCALE.md marker section, like
    # test_committed_ledger_is_valid_and_regenerable: the table is a
    # pure function of the committed cost cards, so regenerating the
    # cards without `--scale --update` must fail here, not silently
    # publish stale numbers.
    from tools.costmodel.__main__ import SCALE_BEGIN, SCALE_DOC, SCALE_END
    text = SCALE_DOC.read_text()
    committed = text.split(SCALE_BEGIN, 1)[1].split(SCALE_END, 1)[0]
    assert committed.strip() == model.scale_markdown(
        model.scale_rows()).strip(), (
        "docs/SCALE.md projection table is stale — run "
        "`python -m tools.costmodel --scale --update`")


# --- validator seeded violations --------------------------------------------

def test_validator_flags_costcard_drift(tmp_path):
    card = model.load("dpos-100k")
    bad = copy.deepcopy(card)
    bad["surprise"] = 1
    del bad["roofline"]
    bad["cost"]["arithmetic_intensity"] = 999.0
    p = tmp_path / "bad_card.json"
    p.write_text(json.dumps(bad))
    errs = validate_trace.validate_costcard(p)
    assert any("surprise" in e for e in errs)
    assert any("missing key 'roofline'" in e for e in errs)
    assert any("arithmetic_intensity" in e for e in errs)
