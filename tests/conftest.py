"""Test harness: run JAX on a virtual 8-device CPU mesh (SURVEY.md §4.4).

Must set env BEFORE jax initializes a backend. Tests exercise the same
shard_map code path that runs on a real v5e-8; bench.py (not under pytest)
uses the real TPU chip.
"""
import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()
