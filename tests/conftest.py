"""Test harness: run JAX on a virtual 8-device CPU mesh (SURVEY.md §4.4).

Tests exercise the same mesh-sharded code path that runs on a real v5e-8;
bench.py (not under pytest) uses the real TPU chip.

The container's axon sitecustomize force-registers the TPU plugin and
overwrites ``JAX_PLATFORMS`` before pytest ever runs, so an env
``setdefault`` is not enough — we must both set the env (for the XLA CPU
client flags) and override the already-imported jax config.
"""
import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (_flags + " --xla_force_host_platform_device_count=8").strip()

import jax

jax.config.update("jax_platforms", "cpu")
assert len(jax.devices()) == 8, (
    f"expected 8 virtual CPU devices, got {jax.devices()} — sharding tests "
    "would silently run unsharded")


def pytest_configure(config):
    # Tier-1 runs `-m 'not slow'` (ROADMAP.md); the slow tier holds the
    # subprocess crash-injection tests (tests/test_resilience.py), each
    # of which pays a full interpreter + jit-compile startup.
    config.addinivalue_line(
        "markers", "slow: subprocess/e2e resilience tests excluded from "
                   "tier-1 (run with -m slow)")
