"""Benchmark-scale differential testing through the CLI front doors.

Until the edge-wise delivery layer (cpp/oracle.cpp Net, EDGE mode) the
oracle materialized the O(N²) delivery matrix per round even under the
capped engines, so cross-engine byte-equivalence — the project's own
acceptance criterion (BASELINE.json:2) — stopped at N ≈ 2k while the
flagship benchmarks run at 100k (VERDICT r5 missing #1). These tests
run the SPEC §3b capped Raft config at 50k nodes through both front
doors — the native ``cpp/consensus-sim`` binary in a subprocess (cpu
engine; auto delivery resolves edge-wise for capped configs) and the
Python CLI's TPU engine in-process (virtual-mesh CPU backend, the same
jit path as the chip) — and byte-compare the digests, making
benchmark-scale differential a routine tier-1 check instead of an
impossibility. The full-size 100k pairings (against the committed
on-chip digests) are recorded in benchmarks/parts/oracle-100k.json.
"""
import json
import pathlib

import pytest

from consensus_tpu import cli

from test_cli import _run_native

# The raft-100k flagship config (benchmarks/run_benchmarks.py) at half
# population — the same SPEC §3b capped semantics and adversary rates,
# sized so the TPU engine's CPU-backend run stays tier-1-friendly
# (~5 s; the edge-wise oracle side is ~1 s).
FLAGS_50K = [
    "--protocol", "raft", "--nodes", "50000", "--rounds", "64",
    "--log-capacity", "128", "--max-entries", "100", "--max-active", "8",
    "--seed", "6", "--drop-rate", "0.01", "--churn-rate", "0.001",
]


def test_native_cli_50k_capped_oracle_matches_tpu_engine(capsys):
    native = _run_native(FLAGS_50K)
    # The edge-wise oracle makes this seconds-class; the dense design
    # needed ~2.5e9 matrix cells per round and could not run at all.
    assert native["wall_s"] < 60, native
    rc = cli.main(FLAGS_50K + ["--engine", "tpu"])
    assert rc == 0
    ours = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert native["digest"] == ours["digest"], (native, ours)
    assert native["payload_bytes"] == ours["payload_bytes"]


def test_native_cli_delivery_flag_digest_invariant():
    # One mid-size capped config through the native front door under all
    # three --oracle-delivery values: same bytes, same digest.
    flags = ["--protocol", "raft", "--nodes", "2048", "--rounds", "24",
             "--log-capacity", "32", "--max-entries", "24", "--max-active",
             "8", "--seed", "12", "--drop-rate", "0.08",
             "--partition-rate", "0.15", "--churn-rate", "0.05"]
    digests = {d: _run_native(flags, extra=["--oracle-delivery", d])["digest"]
               for d in ("auto", "dense", "edge")}
    assert len(set(digests.values())) == 1, digests


def test_native_cli_rejects_delivery_for_dpos():
    with pytest.raises(Exception):
        _run_native(["--protocol", "dpos", "--nodes", "24", "--rounds", "8",
                     "--oracle-delivery", "edge"])


# --- raft-1kx1k: the last differential gap, closed ---------------------------
#
# Dense SPEC §3 semantics at 1024 nodes were long assumed oracle-
# intractable ("~10^13 mixer evals ≈ a day single-core") — the estimate
# was ~100x off: the dense Net materializes one mixer chain per pair
# per round (8 sweeps x 1024 rounds x 1024^2 ≈ 8.6e9 total, ~42 s).
# Every flagship config is now oracle-paired at its true shape.

_PARTS = pathlib.Path(__file__).resolve().parents[1] / "benchmarks/parts"


def _committed_1kx1k():
    tpu = json.loads((_PARTS / "raft-1kx1k.json").read_text())
    oracle_doc = json.loads((_PARTS / "oracle-100k.json").read_text())
    rows = [r for r in oracle_doc["rows"] if r["name"] == "raft-1kx1k"]
    assert rows, "oracle-100k.json lost its raft-1kx1k pairing row"
    return tpu["rows"][0]["tpu"], rows[0]["oracle"]


def test_raft_1kx1k_committed_pairing_is_digest_equal():
    """Tier-1 tripwire over the COMMITTED artifacts: the flagship
    raft-1kx1k on-chip TPU digest and the full-shape oracle digest
    recorded next to it must stay byte-equal, and the oracle row must
    really be the full shape (not a resurrected stand-in)."""
    tpu, oracle = _committed_1kx1k()
    assert tpu["digest"] == oracle["digest"]
    for key in ("n_nodes", "n_rounds", "n_sweeps", "seed"):
        assert oracle["config"][key] == tpu["config"][key], key
    assert oracle["config"]["max_active"] == 0  # dense semantics
    assert oracle["steps"] == tpu["steps"]


@pytest.mark.slow
def test_raft_1kx1k_full_shape_oracle_matches_committed_digest():
    """Recompute the full 8-sweep x 1024-node x 1024-round dense oracle
    run (~42 s single-core) and byte-compare against the committed
    on-chip TPU digest — the raft-1kx1k differential, live."""
    import dataclasses

    from consensus_tpu.core.config import Config
    from consensus_tpu.network import simulator
    tpu, _ = _committed_1kx1k()
    cfg = dataclasses.replace(Config.from_json(json.dumps(tpu["config"])),
                              engine="cpu")
    res = simulator.run(cfg, warmup=False)
    assert res.digest == tpu["digest"]
