"""Benchmark-scale differential testing through the CLI front doors.

Until the edge-wise delivery layer (cpp/oracle.cpp Net, EDGE mode) the
oracle materialized the O(N²) delivery matrix per round even under the
capped engines, so cross-engine byte-equivalence — the project's own
acceptance criterion (BASELINE.json:2) — stopped at N ≈ 2k while the
flagship benchmarks run at 100k (VERDICT r5 missing #1). These tests
run the SPEC §3b capped Raft config at 50k nodes through both front
doors — the native ``cpp/consensus-sim`` binary in a subprocess (cpu
engine; auto delivery resolves edge-wise for capped configs) and the
Python CLI's TPU engine in-process (virtual-mesh CPU backend, the same
jit path as the chip) — and byte-compare the digests, making
benchmark-scale differential a routine tier-1 check instead of an
impossibility. The full-size 100k pairings (against the committed
on-chip digests) are recorded in benchmarks/parts/oracle-100k.json.
"""
import json

import pytest

from consensus_tpu import cli

from test_cli import _run_native

# The raft-100k flagship config (benchmarks/run_benchmarks.py) at half
# population — the same SPEC §3b capped semantics and adversary rates,
# sized so the TPU engine's CPU-backend run stays tier-1-friendly
# (~5 s; the edge-wise oracle side is ~1 s).
FLAGS_50K = [
    "--protocol", "raft", "--nodes", "50000", "--rounds", "64",
    "--log-capacity", "128", "--max-entries", "100", "--max-active", "8",
    "--seed", "6", "--drop-rate", "0.01", "--churn-rate", "0.001",
]


def test_native_cli_50k_capped_oracle_matches_tpu_engine(capsys):
    native = _run_native(FLAGS_50K)
    # The edge-wise oracle makes this seconds-class; the dense design
    # needed ~2.5e9 matrix cells per round and could not run at all.
    assert native["wall_s"] < 60, native
    rc = cli.main(FLAGS_50K + ["--engine", "tpu"])
    assert rc == 0
    ours = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert native["digest"] == ours["digest"], (native, ours)
    assert native["payload_bytes"] == ours["payload_bytes"]


def test_native_cli_delivery_flag_digest_invariant():
    # One mid-size capped config through the native front door under all
    # three --oracle-delivery values: same bytes, same digest.
    flags = ["--protocol", "raft", "--nodes", "2048", "--rounds", "24",
             "--log-capacity", "32", "--max-entries", "24", "--max-active",
             "8", "--seed", "12", "--drop-rate", "0.08",
             "--partition-rate", "0.15", "--churn-rate", "0.05"]
    digests = {d: _run_native(flags, extra=["--oracle-delivery", d])["digest"]
               for d in ("auto", "dense", "edge")}
    assert len(set(digests.values())) == 1, digests


def test_native_cli_rejects_delivery_for_dpos():
    with pytest.raises(Exception):
        _run_native(["--protocol", "dpos", "--nodes", "24", "--rounds", "8",
                     "--oracle-delivery", "edge"])
