"""The RETIRED global-pacemaker HotStuff round, kept verbatim as a
test-only reference (PR "view-desync", the PR 8 twin playbook).

This is the engines/hotstuff.py kernel as committed before the SPEC §B
per-node view synchronizer: the pacemaker (`gview`, `gtimer`) is ONE
scalar pair per sweep — the whole network idealized as agreeing on the
current view — the leader is the global `gview mod N`, and a node's
`view` field merely records the last view it synced to.

Job: bit-identity anchor for the synchronizer's sync path —
tests/test_hotstuff.py drives this round and the production per-node
round through the SAME runner over configs whose views stay in
lockstep (zero delivery-fault rates; churn / silent & equivocating byz
allowed — both stall every node identically) and asserts the decided
logs, chain state, and per-node prefixes are identical, with the
production per-node `view` equal to the retired GLOBAL `gview`
(production view[i] tracks the node's OWN pacemaker, one ahead of the
retired sync record). Any pacemaker regression that shifts the sync
path shows up here, not three PRs later in an oracle differential.
"""
from __future__ import annotations

from typing import NamedTuple

import jax.numpy as jnp

from consensus_tpu.core import rng
from consensus_tpu.core.config import Config
from consensus_tpu.engines.hotstuff import (FORK_TABLE, HOTSTUFF_TELEMETRY,
                                            _block_val)
from consensus_tpu.network.runner import EngineDef
from consensus_tpu.ops.adversary import (crash_counts, crash_transition,
                                         delayed_open, freeze_down,
                                         safety_counts)
from consensus_tpu.ops.adversary import cutoff as _lt
from consensus_tpu.ops.adversary import draw as _draw
from consensus_tpu.ops.aggregate import (agg_counts, agg_ids, agg_poison,
                                         agg_round, downlink, poison_count,
                                         seg_sum, seg_widths, take_seg,
                                         uplink_edge, uplink_lies)
from consensus_tpu.ops.viewsync import sync_counts


class RefHotstuffState(NamedTuple):
    """The retired carry: the production fields plus the global
    pacemaker scalars the synchronizer distributed into view/timer."""
    seed: jnp.ndarray
    gview: jnp.ndarray      # [] i32 — the retired global pacemaker view
    gtimer: jnp.ndarray     # [] i32 — rounds spent in the current view
    b1_v: jnp.ndarray
    b1_h: jnp.ndarray
    b2_v: jnp.ndarray
    b2_h: jnp.ndarray
    b3_v: jnp.ndarray
    b3_h: jnp.ndarray
    gcommit: jnp.ndarray
    chain_v: jnp.ndarray
    chain_vid: jnp.ndarray
    fvec: jnp.ndarray
    ftab_v: jnp.ndarray
    ftab_h: jnp.ndarray
    fnum: jnp.ndarray
    view: jnp.ndarray       # [N] i32 — last view node i SYNCED to
    timer: jnp.ndarray
    clen: jnp.ndarray
    down: jnp.ndarray


def ref_hotstuff_init(cfg: Config, seed) -> RefHotstuffState:
    N, S = cfg.n_nodes, cfg.log_capacity
    z = jnp.int32(0)
    none = jnp.int32(-1)
    return RefHotstuffState(
        jnp.asarray(seed, jnp.uint32), z, z, none, none, none, none,
        none, none, z, jnp.full((S,), -1, jnp.int32),
        jnp.zeros(S, jnp.int32), jnp.zeros(N, jnp.int32),
        jnp.full((FORK_TABLE,), -1, jnp.int32),
        jnp.full((FORK_TABLE,), -1, jnp.int32), z,
        jnp.zeros(N, jnp.int32), jnp.zeros(N, jnp.int32),
        jnp.zeros(N, jnp.int32), jnp.zeros(N, bool))


def global_pacemaker_round(cfg: Config, st: RefHotstuffState, r, *,
                           telem: bool = False):
    """The retired global-pacemaker round, verbatim."""
    N, S = cfg.n_nodes, cfg.log_capacity
    Q = 2 * cfg.f + 1
    seed = st.seed
    ur = jnp.asarray(r, jnp.uint32)
    idx = jnp.arange(N, dtype=jnp.int32)
    uidx = idx.astype(jnp.uint32)

    crash_on = cfg.crash_on
    down = st.down
    view, timer, clen = st.view, st.timer, st.clen
    if crash_on:
        down, rec, _crashed = crash_transition(
            seed, ur, down, cfg.crash_cutoff, cfg.recover_cutoff,
            cfg.max_crashed)
        view = jnp.where(rec, 0, view)
        timer = jnp.where(rec, 0, timer)
        frozen = (view, timer, clen)

    churn = _draw(seed, rng.STREAM_CHURN, ur, 0, 0) < _lt(cfg.churn_cutoff)

    L = st.gview % jnp.int32(N)
    uL = L.astype(jnp.uint32)
    honest = idx < (N - cfg.n_byzantine)
    h_next = st.b1_h + 1
    equiv = cfg.byz_mode == "equivocate" and cfg.n_byzantine > 0
    byzL = L >= jnp.int32(N - cfg.n_byzantine)
    if equiv:
        proposing = ~churn & (h_next < S)
    else:
        proposing = ~churn & ~byzL & (h_next < S)
    if crash_on:
        proposing &= ~down[L]

    switch = cfg.switch_on
    open_p = ~(rng.delivery_u32_jnp(seed, ur, uL, uidx)
               < _lt(cfg.drop_cutoff))
    if cfg.max_delay_rounds > 0:
        open_p |= delayed_open(seed, ur, uL, uidx, cfg.drop_cutoff,
                               cfg.max_delay_rounds)
    if not switch:
        open_v = ~(rng.delivery_u32_jnp(seed, ur, uidx, uL)
                   < _lt(cfg.drop_cutoff))
        if cfg.max_delay_rounds > 0:
            open_v |= delayed_open(seed, ur, uidx, uL, cfg.drop_cutoff,
                                   cfg.max_delay_rounds)
    part_active = (_draw(seed, rng.STREAM_PARTITION, ur, 0, 0)
                   < _lt(cfg.partition_cutoff))
    side = _draw(seed, rng.STREAM_PARTITION, ur, 1, uidx) & jnp.uint32(1)
    side_L = _draw(seed, rng.STREAM_PARTITION, ur, 1, uL) & jnp.uint32(1)
    same_side = (side == side_L) | ~part_active

    pdel = proposing & ((idx == L) | (open_p & same_side))
    if crash_on:
        pdel &= ~down

    vote = pdel & honest
    if equiv:
        evid = jnp.where(byzL,
                         (_draw(seed, rng.STREAM_EQUIV, ur, uL, uidx)
                          & jnp.uint32(1)).astype(jnp.int32),
                         0)
        voteb = pdel & ~honest
    if switch:
        aggst = agg_round(cfg, seed, ur)
        K_agg = cfg.n_aggregators
        sids = agg_ids(N, K_agg)
        up0 = uplink_edge(cfg, seed, aggst, 0)
        if crash_on:
            up0 &= ~down
        down0 = downlink(cfg, seed, ur, aggst, 0, jnp.reshape(L, (1,)))[:, 0]
        pz0 = agg_poison(cfg, seed, ur, 0)
        wid = seg_widths(jnp.ones(N, bool), sids, K_agg) \
            if pz0 is not None else None
        lie, _fv = uplink_lies(cfg, seed, ur, ~honest)

        def _served(segx):
            srv = jnp.where(down0, segx, 0)
            if pz0 is not None:
                srv = jnp.where(down0 & pz0, wid, srv)
            return jnp.sum(srv)

        if pz0 is not None:
            own = take_seg((pz0 & down0).astype(jnp.int32), sids,
                           K_agg)[L].astype(bool)

        def _count(sup, self_sup):
            contrib = sup & (idx != L) & up0
            seg = seg_sum(contrib.astype(jnp.int32), sids, K_agg)
            s = self_sup.astype(jnp.int32)
            if pz0 is not None:
                s = jnp.where(own, 0, s)
            return s + _served(seg)

        if equiv:
            claim = (voteb | lie) if lie is not None else voteb
            sup0 = (vote & (evid == 0)) | claim
            sup1 = (vote & (evid == 1)) | claim
            cnt0 = _count(sup0, sup0[L])
            cnt1 = _count(sup1, sup1[L])
        else:
            sup = (vote | lie) if lie is not None else vote
            cnt = _count(sup, vote[L])
    else:
        pz0 = None
        if equiv:
            vd0 = ((vote & (evid == 0)) | voteb) & ((idx == L) | open_v)
            vd1 = ((vote & (evid == 1)) | voteb) & ((idx == L) | open_v)
            cnt0 = jnp.sum(vd0.astype(jnp.int32))
            cnt1 = jnp.sum(vd1.astype(jnp.int32))
        else:
            vdel = vote & ((idx == L) | open_v)
            cnt = jnp.sum(vdel.astype(jnp.int32))
    if equiv:
        qc0 = proposing & (cnt0 >= Q)
        qc1 = proposing & (cnt1 >= Q)
        qc = qc0 | qc1
        forked = qc0 & qc1
        vid = jnp.where(qc0, jnp.int32(0), jnp.int32(1))
        cnt = cnt0 + cnt1
    else:
        qc = proposing & (cnt >= Q)

    b1_v = jnp.where(qc, st.gview, st.b1_v)
    b1_h = jnp.where(qc, h_next, st.b1_h)
    b2_v = jnp.where(qc, st.b1_v, st.b2_v)
    b2_h = jnp.where(qc, st.b1_h, st.b2_h)
    b3_v = jnp.where(qc, st.b2_v, st.b3_v)
    b3_h = jnp.where(qc, st.b2_h, st.b3_h)
    sarange = jnp.arange(S, dtype=jnp.int32)
    chain_v = jnp.where((sarange == h_next) & qc, st.gview, st.chain_v)
    consec = (b3_v >= 0) & (b1_v == b2_v + 1) & (b2_v == b3_v + 1)
    gcommit = jnp.where(qc & consec,
                        jnp.maximum(st.gcommit, b3_h + 1), st.gcommit)

    if equiv:
        chain_vid = jnp.where((sarange == h_next) & qc, vid, st.chain_vid)
        deceived = pdel & honest & (evid == 1)
        can = forked & (st.fnum < FORK_TABLE)
        hot = (jnp.arange(FORK_TABLE, dtype=jnp.int32) == st.fnum) & can
        ftab_v = jnp.where(hot, st.gview, st.ftab_v)
        ftab_h = jnp.where(hot, h_next, st.ftab_h)
        fbit = jnp.left_shift(jnp.int32(1),
                              jnp.minimum(st.fnum, FORK_TABLE - 1))
        fvec = jnp.where(can & deceived, st.fvec | fbit, st.fvec)
        fnum = st.fnum + can.astype(jnp.int32)
    else:
        chain_vid, fvec = st.chain_vid, st.fvec
        ftab_v, ftab_h, fnum = st.ftab_v, st.ftab_h, st.fnum

    view = jnp.where(pdel, st.gview, view)
    clen = jnp.where(pdel, jnp.maximum(clen, st.gcommit), clen)
    timer = jnp.where(pdel, 0, timer + 1)

    to = ~qc & (st.gtimer + 1 >= cfg.view_timeout)
    adv = qc | to
    gview = st.gview + adv.astype(jnp.int32)
    gtimer = jnp.where(adv, 0, st.gtimer + 1)

    if crash_on:
        view, timer, clen = freeze_down(down, frozen, (view, timer, clen))

    new = RefHotstuffState(seed, gview, gtimer, b1_v, b1_h, b2_v, b2_h,
                           b3_v, b3_h, gcommit, chain_v, chain_vid, fvec,
                           ftab_v, ftab_h, fnum, view, timer, clen, down)
    if not telem:
        return new
    cz = crash_counts(_crashed, rec, down) if crash_on else crash_counts()
    az = agg_counts(aggst, poison_count(aggst, pz0)) if switch \
        else agg_counts()
    if equiv:
        conf = jnp.zeros((), jnp.int32)
        for k in range(FORK_TABLE):
            inw = ((jnp.int32(k) < fnum) & (ftab_h[k] >= st.clen)
                   & (ftab_h[k] < new.clen))
            conf += jnp.sum((((fvec >> k) & 1).astype(bool)
                             & inw).astype(jnp.int32))
        sz = safety_counts(forked, conf)
    else:
        sz = safety_counts()
    # SPEC §B tail (zeros — the retired round predates the synchronizer
    # and is only ever compared on lockstep configs, where the
    # production sync counters are identically zero too).
    vec = jnp.stack([qc.astype(jnp.int32),
                     gcommit - st.gcommit,
                     jnp.sum(new.clen - st.clen),
                     to.astype(jnp.int32),
                     jnp.sum(pdel.astype(jnp.int32)),
                     cnt, *cz, *az, *sz, *sync_counts()])
    return new, vec


def global_pacemaker_round_telem(cfg: Config, st: RefHotstuffState, r):
    return global_pacemaker_round(cfg, st, r, telem=True)


def _ref_extract(st: RefHotstuffState) -> dict:
    """The production extraction epilogue applied to the retired carry,
    PLUS the global pacemaker scalars — the twin test maps the
    production per-node `view` onto the retired `gview`."""
    S = st.chain_v.shape[-1]
    sarange = jnp.arange(S, dtype=jnp.int32)
    committed = sarange[None, None, :] < st.clen[..., None]
    v0 = _block_val(st.seed[..., None], st.chain_v, sarange[None, :])
    v1 = _block_val(st.seed[..., None], st.chain_v, sarange[None, :], sub=6)
    base = jnp.where(st.chain_vid == 1, v1, v0)
    dval = jnp.where(committed, base[..., None, :], 0)
    for k in range(FORK_TABLE):
        ok = jnp.int32(k) < st.fnum
        hh = st.ftab_h[..., k]
        alt = _block_val(st.seed, st.ftab_v[..., k], hh, sub=6)
        hit = (((st.fvec >> k) & 1).astype(bool)[..., None]
               & (sarange == hh[..., None, None])
               & ok[..., None, None] & committed)
        dval = jnp.where(hit, alt[..., None, None], dval)
    return {"committed": committed, "dval": dval,
            "clen": st.clen, "gcommit": st.gcommit,
            "chain_v": st.chain_v, "view": st.view,
            "fvec": st.fvec, "fnum": st.fnum,
            "gview": st.gview, "gtimer": st.gtimer}


def _ref_pspec(cfg: Config) -> RefHotstuffState:
    from jax.sharding import PartitionSpec as P

    from consensus_tpu.parallel.mesh import NODE_AXIS as ND
    g, v = P(), P(ND)
    return RefHotstuffState(seed=g, gview=g, gtimer=g, b1_v=g, b1_h=g,
                            b2_v=g, b2_h=g, b3_v=g, b3_h=g, gcommit=g,
                            chain_v=P(None), chain_vid=P(None), fvec=v,
                            ftab_v=P(None), ftab_h=P(None), fnum=g,
                            view=v, timer=v, clen=v, down=v)


def reference_engine() -> EngineDef:
    """The retired round behind the production EngineDef seam, so tests
    drive it through the same runner/chunk machinery as the real one."""
    return EngineDef("hotstuff-retired", ref_hotstuff_init,
                     global_pacemaker_round, _ref_extract, _ref_pspec,
                     telemetry_names=HOTSTUFF_TELEMETRY,
                     round_telem=global_pacemaker_round_telem)
