"""Carry donation in the chunked round loop (ROADMAP bandwidth lever).

``runner._chunk_jit`` donates its carry (and telemetry accumulator):
XLA aliases every input buffer to its same-shaped output
(``input_output_alias``, statically enforced by tools/hlocheck's
donation contract), so a chunked run holds ONE carry across dispatches
instead of two. Donation is an allocation strategy, not a semantic
change — these tests pin that across all six engines, including the
two paths where a stale reference could observe the buffer reuse:

  * the async checkpoint writer (its pending snapshot must be a COPY —
    runner._snapshot_copy — or the writer-thread pull races the next
    dispatch's buffer reuse);
  * grouped sweep_chunk execution (per-group sub-runs each donate).

The bit-identity reference is ``undonated_chunk``
(tests/fixtures/hlocheck/bad_engines.py): the same vmap+scan semantics
with no ``donate_argnums``.
"""
import dataclasses
import pathlib
import sys

import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, str(pathlib.Path(__file__).parent))

from fixtures.hlocheck.bad_engines import undonated_chunk  # noqa: E402

from consensus_tpu.core.config import Config  # noqa: E402
from consensus_tpu.network import runner, simulator  # noqa: E402

ADV = dict(drop_rate=0.1, partition_rate=0.05, churn_rate=0.05)

# One config per engine — all six (simulator.engine_def dispatch).
CFGS = {
    "raft": Config(protocol="raft", n_nodes=8, n_rounds=24, n_sweeps=4,
                   log_capacity=16, max_entries=8, **ADV),
    "raft-sparse": Config(protocol="raft", n_nodes=16, n_rounds=24,
                          n_sweeps=4, log_capacity=16, max_entries=8,
                          max_active=4, **ADV),
    "pbft": Config(protocol="pbft", f=1, n_nodes=4, n_rounds=16,
                   n_sweeps=4, log_capacity=8, **ADV),
    "pbft-bcast": Config(protocol="pbft", fault_model="bcast", f=5,
                         n_nodes=16, n_rounds=16, n_sweeps=4,
                         log_capacity=8, **ADV),
    "paxos": Config(protocol="paxos", n_nodes=8, n_rounds=16, n_sweeps=4,
                    log_capacity=8, **ADV),
    "dpos": Config(protocol="dpos", n_nodes=16, n_rounds=16, n_sweeps=4,
                   log_capacity=32, n_candidates=8, n_producers=2,
                   epoch_len=8, **ADV),
}


def _assert_same(a: dict, b: dict) -> None:
    assert a.keys() == b.keys()
    for k in a:
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)


@pytest.mark.parametrize("name", sorted(CFGS))
def test_donated_chunk_bit_identical_to_undonated(name):
    cfg = CFGS[name]
    eng = simulator.engine_def(cfg)
    assert eng.name == name
    seeds = jnp.asarray(runner.make_seeds(cfg))
    ref = undonated_chunk(cfg, eng, cfg.n_rounds,
                          runner._init_jit(cfg, eng, seeds), jnp.int32(0))
    donated_in = runner._init_jit(cfg, eng, seeds)
    out = runner._chunk_jit(cfg, eng, cfg.n_rounds, donated_in,
                            jnp.int32(0))
    import jax
    for i, (a, b) in enumerate(zip(jax.tree.leaves(ref),
                                   jax.tree.leaves(out))):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b),
                                      err_msg=f"{name} leaf {i}")
    # Donation really happened at runtime: the input buffers are gone
    # (is_deleted is the live witness of the aliasing hlocheck pins
    # statically).
    assert all(leaf.is_deleted() for leaf in jax.tree.leaves(donated_in))


@pytest.mark.parametrize("name", ["raft-sparse", "pbft-bcast"])
def test_async_checkpoint_run_bit_identical_under_donation(name, tmp_path):
    """The donated-buffer × async-writer interplay: the writer's pending
    snapshot is a _snapshot_copy, so chunk k+1's buffer reuse never
    races the background pull — results AND the written snapshot's
    resume both stay bit-identical to the plain run."""
    cfg = dataclasses.replace(CFGS[name], scan_chunk=6)
    eng = simulator.engine_def(cfg)
    base = runner.run(cfg, eng)
    ck = tmp_path / "ck.npz"
    ckpt = runner.run(cfg, eng, checkpoint_path=ck)         # async writer
    _assert_same(base, ckpt)
    sync = runner.run(cfg, eng, checkpoint_path=tmp_path / "ck2.npz",
                      sync_checkpoints=True)
    _assert_same(base, sync)
    # The mid-run snapshot the writer copied out resumes bit-identically.
    assert runner.peek_checkpoint(ck, cfg) is not None
    resumed = runner.run(cfg, eng, checkpoint_path=ck, resume=True)
    _assert_same(base, resumed)


@pytest.mark.parametrize("name", ["raft-sparse", "pbft-bcast"])
def test_sweep_chunk_groups_bit_identical_under_donation(name):
    cfg = CFGS[name]
    eng = simulator.engine_def(cfg)
    base = runner.run(cfg, eng)
    grouped = runner.run(dataclasses.replace(cfg, sweep_chunk=3), eng)
    _assert_same(base, grouped)


def test_telemetry_accumulator_donated_and_neutral():
    """telem rides donate_argnums=(3, 5): accumulation is unchanged and
    the run stays digest-neutral (tests/test_obs.py covers all engines;
    this pins the donated-accumulator path end to end)."""
    cfg = dataclasses.replace(CFGS["raft-sparse"], scan_chunk=6)
    eng = simulator.engine_def(cfg)
    base = runner.run(cfg, eng)
    stats: dict = {}
    telem = runner.run(cfg, eng, telemetry=True, stats=stats)
    _assert_same(base, telem)
    total = sum(int(v.sum()) for v in stats["telemetry"].values())
    assert total > 0
