"""SPEC §3c Raft byzantine minority: JAX↔oracle byte-equivalence for both
byz modes on the dense (§3) and capped (§3b) engines, liveness degradation
under `silent`, and the SPEC-promised demonstration that `equivocate` with
enough byz voters elects two leaders in one term and diverges honest logs
(Raft is NOT Byzantine fault-tolerant — the simulator shows the attack).

Every byz branch in engines/raft.py (withhold/double_grant in P2/P3) and
engines/raft_sparse.py has a differential test here (VERDICT r4 weak #3).
"""
import dataclasses

import numpy as np
import pytest

from consensus_tpu import Config

from helpers import committed_prefixes_agree, run_cached, trace_raft_rounds


def _cfg(**kw):
    base = dict(protocol="raft", n_nodes=5, n_rounds=96, log_capacity=64,
                max_entries=40, n_sweeps=2, seed=17, n_byzantine=1,
                byz_mode="silent")
    base.update(kw)
    return Config(**base)


# Coverage grid: both modes x {dense, capped} x {clean, dropped, hostile}.
# Capped rows exercise raft_sparse.py's byz branches (active-set exclusion
# of silent byz candidates, edge-wise double-grant tally).
CONFIGS = [
    ("silent-dense", _cfg()),
    ("silent-dense-drops", _cfg(n_byzantine=2, drop_rate=0.2, seed=23)),
    ("silent-dense-hostile", _cfg(n_nodes=9, n_byzantine=3, drop_rate=0.3,
                                  partition_rate=0.15, churn_rate=0.05,
                                  n_rounds=128, seed=29)),
    ("equiv-dense", _cfg(byz_mode="equivocate", n_byzantine=2,
                         drop_rate=0.25, seed=0)),
    ("equiv-dense-hostile", _cfg(byz_mode="equivocate", n_nodes=9,
                                 n_byzantine=4, drop_rate=0.35,
                                 churn_rate=0.1, n_rounds=128, seed=31)),
    ("silent-capped", _cfg(max_active=2, n_byzantine=2, drop_rate=0.2,
                           seed=37)),
    ("silent-capped-wide", _cfg(max_active=4, n_nodes=11, n_byzantine=4,
                                drop_rate=0.3, churn_rate=0.1, seed=41)),
    ("equiv-capped", _cfg(max_active=2, byz_mode="equivocate",
                          n_byzantine=2, drop_rate=0.25, seed=43)),
    ("equiv-capped-wide", _cfg(max_active=4, n_nodes=11, byz_mode="equivocate",
                               n_byzantine=5, drop_rate=0.35, seed=47)),
]


@pytest.mark.parametrize("tag,cfg", CONFIGS, ids=[t for t, _ in CONFIGS])
def test_byz_differential_vs_oracle(tag, cfg):
    tpu = run_cached(dataclasses.replace(cfg, engine="tpu"))
    cpu = run_cached(dataclasses.replace(cfg, engine="cpu"))
    assert tpu.payload == cpu.payload, (tag, tpu.digest, cpu.digest)


def test_capped_byz_equals_dense_when_cap_not_binding():
    """With A = N the §3b active set never suppresses anyone, so the capped
    byz semantics must reproduce the dense byz decided logs bit-for-bit."""
    for mode in ("silent", "equivocate"):
        base = _cfg(byz_mode=mode, n_byzantine=2, drop_rate=0.2, seed=53)
        dense = run_cached(base)
        capped = run_cached(dataclasses.replace(base, max_active=5))
        assert dense.payload == capped.payload, mode


def test_silent_majority_minority_kills_liveness():
    """SPEC §3c silent: byz nodes send nothing. With 3 byz of N=5 the
    honest subset (2) is below majority (3), so no candidate can ever
    assemble a quorum — no leader, no commits, on every sweep and seed."""
    cfg = _cfg(n_byzantine=3, n_sweeps=4, seed=59)
    res = run_cached(cfg)
    assert res.counts.max() == 0
    out = run_cached(dataclasses.replace(cfg, engine="cpu"))
    assert out.counts.max() == 0


def test_silent_degrades_liveness_vs_clean():
    """With 2 byz of N=5 silent, commit quorums need all three honest acks
    per round; under drops, progress is measurably slower than the same
    seeds with no byz nodes (liveness degradation, SPEC §3c)."""
    byz = run_cached(_cfg(n_byzantine=2, drop_rate=0.25, n_sweeps=4,
                          n_rounds=48, max_entries=100, log_capacity=128,
                          seed=61))
    clean = run_cached(_cfg(n_byzantine=0, drop_rate=0.25, n_sweeps=4,
                            n_rounds=48, max_entries=100, log_capacity=128,
                            seed=61))
    assert byz.counts.sum() < clean.counts.sum()


def test_silent_preserves_safety():
    """Withholding messages is within Raft's fault model: committed
    prefixes of ALL nodes (byz ones update state normally) must agree."""
    cfg = _cfg(n_byzantine=2, drop_rate=0.3, churn_rate=0.1, n_sweeps=4,
               n_rounds=128, seed=67)
    res = run_cached(cfg)
    for b in range(cfg.n_sweeps):
        assert committed_prefixes_agree(res, list(range(cfg.n_nodes)), b)


# --- the election-safety attack (SPEC §3c equivocate) -----------------------

# Verified by seed search: sweep seed 0 at drop_rate=0.25 elects two honest
# leaders in term 1 (nodes 0 and 1) and diverges honest committed logs.
ATTACK = Config(protocol="raft", n_nodes=5, n_rounds=128, log_capacity=64,
                max_entries=40, n_sweeps=1, seed=0, drop_rate=0.25,
                n_byzantine=2, byz_mode="equivocate")


def test_equivocate_elects_two_leaders_one_term():
    """The attack works: some term has >= 2 distinct winners (Election
    Safety broken), which honest-node Raft makes impossible."""
    trace = trace_raft_rounds(ATTACK)
    winners = {}
    for r in range(ATTACK.n_rounds):
        for i in np.nonzero(trace["role"][r] == 2)[0]:
            winners.setdefault(int(trace["term"][r, i]), set()).add(int(i))
    multi = {t: w for t, w in winners.items() if len(w) > 1}
    assert multi, f"attack did not fire; winners per term: {winners}"


def test_equivocate_diverges_honest_committed_logs():
    """State-Machine Safety broken among HONEST nodes: two committed
    prefixes disagree — the observable damage of the split election."""
    res = run_cached(ATTACK)
    H = ATTACK.n_nodes - ATTACK.n_byzantine
    assert not committed_prefixes_agree(res, list(range(H)), 0), \
        "honest committed logs did not diverge"


def test_equivocate_attack_is_engine_exact():
    """However broken the run, both engines must agree byte-for-byte —
    the adversary is a deterministic function of the same draws."""
    tpu = run_cached(dataclasses.replace(ATTACK, engine="tpu"))
    cpu = run_cached(dataclasses.replace(ATTACK, engine="cpu"))
    assert tpu.payload == cpu.payload
