"""SPEC Appendix A adversary scenario library (+ the §6c oracle mirror).

Five contracts under test, per the PR's acceptance criteria:

  1. **Zero-rate no-ops** — the new fault knobs at rest (miss_rate = 0,
     max_delay_rounds = 0 or un-droppable, attack_rate = 0) are
     bit-identical to the adversary-free run per engine (the compiled
     no-op side is pinned by the byte-stable hlocheck fingerprints).
  2. **Oracle parity** — every new fault (and §6c crash, newly
     mirrored) is byte-differential against the C++ oracle at N <= 2k,
     for every protocol/engine/fault composition, under both oracle
     delivery strategies; crash_prob is now ACCEPTED on engine="cpu".
  3. **Attack semantics** — SPEC §A.3: "elect" jams every election in
     an attacked round (per-round telemetry proves it, dense + capped
     engines); "sticky" pins the target's leadership against churn the
     control run loses.
  4. **LIB under gaps** — miss_rate > 0 produces chain-wide gaps,
     lib_index matches an independent brute-force over gappy schedules,
     and LIB stalls when > 1/3 of the producer set misses (crafted
     chains + a saturated end-to-end run).
  5. **Scenario layer** — every shipped scenario passes its timeline
     assertions in-test; the supervisor degrades crash configs to the
     (now-mirrored) oracle and dies loudly on TPU-only attacks; the
     checkpoint layer treats adversary knobs as trajectory identity.
"""
import dataclasses
import json
import pathlib
import subprocess

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensus_tpu import scenarios
from consensus_tpu.core.config import Config
from consensus_tpu.engines.dpos import lib_index
from consensus_tpu.network import faults, runner, simulator, supervisor

from helpers import run_cached, trace_raft_rounds

CPP_DIR = pathlib.Path(__file__).resolve().parents[1] / "cpp"

# Small-but-adversarial shapes, one per engine path.
CFGS = {
    "raft": Config(protocol="raft", n_nodes=9, n_rounds=48, n_sweeps=2,
                   log_capacity=16, max_entries=12, seed=5, drop_rate=0.3),
    "raft-sparse": Config(protocol="raft", n_nodes=64, max_active=6,
                          n_rounds=48, n_sweeps=2, log_capacity=16,
                          max_entries=12, seed=5, drop_rate=0.3),
    "pbft": Config(protocol="pbft", f=2, n_nodes=7, n_rounds=48,
                   log_capacity=8, seed=5, drop_rate=0.3),
    "pbft-bcast": Config(protocol="pbft", fault_model="bcast", f=2,
                         n_nodes=7, n_rounds=48, log_capacity=8, seed=5,
                         drop_rate=0.3),
    "paxos": Config(protocol="paxos", n_nodes=9, n_rounds=48, n_sweeps=2,
                    log_capacity=8, seed=5, drop_rate=0.3),
    "dpos": Config(protocol="dpos", n_nodes=24, n_rounds=48,
                   log_capacity=64, n_candidates=12, n_producers=5,
                   epoch_len=8, seed=5, drop_rate=0.3),
}
CRASH = dict(crash_prob=0.15, recover_prob=0.3, max_crashed=3)
DELAY = dict(max_delay_rounds=4, partition_rate=0.1, churn_rate=0.05)


def _cpu(cfg, **kw):
    return simulator.run(dataclasses.replace(cfg, engine="cpu"),
                         warmup=False, **kw)


def _round_telem(cfg):
    """Per-round telemetry vectors [R, K] for sweep 0 — the per-round
    probe final totals cannot provide."""
    eng = simulator.engine_def(cfg)
    seeds = jnp.asarray(runner.make_seeds(cfg))

    def go(seed):
        def body(c, r):
            c2, vec = eng.round_telem(cfg, c, r)
            return c2, vec
        _, out = jax.lax.scan(body, eng.make_carry(cfg, seed),
                              jnp.arange(cfg.n_rounds, dtype=jnp.int32))
        return out

    return np.asarray(jax.jit(go)(seeds[0])), list(eng.telemetry_names)


# --- 1. zero-rate no-ops ----------------------------------------------------

@pytest.mark.parametrize("name", list(CFGS))
def test_delay_without_drops_is_identity(name):
    """A delayed retransmission repairs a DROP; with drop_rate = 0 no
    flight is ever dropped, so any max_delay_rounds must be
    bit-invisible — the semantic zero-rate contract (the compiled
    max_delay_rounds = 0 no-op is pinned by the byte-stable hlocheck
    fingerprints)."""
    cfg = dataclasses.replace(CFGS[name], drop_rate=0.0)
    delayed = dataclasses.replace(cfg, max_delay_rounds=8)
    assert simulator.run(delayed, warmup=False).payload \
        == run_cached(cfg).payload


def test_attack_rate_zero_is_identity():
    cfg = CFGS["raft"]
    off = dataclasses.replace(cfg, attack="elect", attack_rate=0.0)
    assert simulator.run(off, warmup=False).payload \
        == run_cached(cfg).payload
    off_s = dataclasses.replace(CFGS["raft-sparse"], attack="sticky",
                                attack_rate=0.0, attack_target=3)
    assert simulator.run(off_s, warmup=False).payload \
        == run_cached(CFGS["raft-sparse"]).payload


def test_miss_rate_zero_is_identity():
    cfg = CFGS["dpos"]
    # An explicit zero next to other live adversaries must not perturb.
    off = dataclasses.replace(cfg, miss_rate=0.0, churn_rate=0.05)
    on_base = dataclasses.replace(cfg, churn_rate=0.05)
    assert simulator.run(off, warmup=False).payload \
        == simulator.run(on_base, warmup=False).payload


# --- 2. oracle parity -------------------------------------------------------

def test_config_accepts_crash_on_cpu_engine():
    cfg = Config(protocol="raft", engine="cpu", crash_prob=0.1,
                 recover_prob=0.2)
    assert cfg.crash_cutoff > 0  # the old rejection is lifted


@pytest.mark.parametrize("name", list(CFGS))
def test_crash_oracle_parity(name):
    cfg = dataclasses.replace(CFGS[name], **CRASH)
    assert run_cached(cfg).digest == _cpu(cfg).digest


@pytest.mark.parametrize("name", list(CFGS))
def test_delay_oracle_parity(name):
    cfg = dataclasses.replace(CFGS[name], **DELAY)
    want = run_cached(cfg).digest
    assert want == _cpu(cfg).digest
    if name != "dpos":  # dpos has no delivery-strategy switch
        for strategy in ("dense", "edge") if name != "pbft-bcast" \
                else ("dense",):
            assert want == _cpu(cfg, oracle_delivery=strategy).digest, \
                f"{name} diverges under oracle_delivery={strategy}"


def test_miss_oracle_parity():
    cfg = dataclasses.replace(CFGS["dpos"], miss_rate=0.4)
    assert run_cached(cfg).digest == _cpu(cfg).digest


def test_everything_composed_oracle_parity():
    """All the new faults at once, on the protocol that now attacks its
    own mechanism — the flagship-style adversarial config class."""
    cfg = dataclasses.replace(CFGS["dpos"], miss_rate=0.3, **CRASH, **DELAY)
    res = run_cached(cfg)
    assert res.digest == _cpu(cfg).digest
    # LIB derives engine-independently from the decided chains.
    np.testing.assert_array_equal(res.extras["lib"],
                                  _cpu(cfg).extras["lib"])


def test_byz_crash_delay_compose_oracle_parity():
    cfg = dataclasses.replace(CFGS["raft-sparse"], n_byzantine=4,
                              byz_mode="equivocate", **CRASH, **DELAY)
    assert run_cached(cfg).digest == _cpu(cfg).digest


# --- 3. targeted-attack semantics (SPEC §A.3) -------------------------------

@pytest.mark.parametrize("name", ["raft", "raft-sparse"])
def test_elect_jams_every_attacked_election(name):
    """In any round the jam fired (attack_rounds telemetry = 1), NO
    candidate may win — and the attack must actually fire (else the
    test is vacuous) yet not prevent eventual elections."""
    cfg = dataclasses.replace(CFGS[name], n_rounds=64, drop_rate=0.05,
                              attack="elect", attack_rate=0.8, seed=11)
    vecs, names = _round_telem(cfg)
    atk = vecs[:, names.index("attack_rounds")]
    wins = vecs[:, names.index("leader_elections")]
    assert atk.sum() > 0, "attack never fired — vacuous"
    assert wins[atk > 0].sum() == 0, \
        "a leader was elected in a jammed round"
    assert wins.sum() > 0, "elections never slipped through"


def test_sticky_leader_never_steps_down():
    """Once the target holds leadership, churn and term pressure the
    control run yields to cannot dislodge it (inbound jammed, step-down
    skipped) — while the attack-free control DOES lose its leader."""
    base = Config(protocol="raft", n_nodes=5, n_rounds=96,
                  log_capacity=64, max_entries=48, seed=3,
                  churn_rate=0.3, drop_rate=0.1)
    tgt = 0
    tr = trace_raft_rounds(dataclasses.replace(
        base, attack="sticky", attack_target=tgt))
    role = tr["role"]                                   # [R, N]
    lead = np.nonzero(role[:, tgt] == 2)[0]
    assert lead.size, "target never became leader — vacuous"
    first = int(lead[0])
    assert (role[first:, tgt] == 2).all(), \
        "sticky target stepped down despite the attack"
    ctrl = trace_raft_rounds(base)["role"]
    clead = np.nonzero(ctrl[:, tgt] == 2)[0]
    if clead.size:  # control target led at some point...
        assert not (ctrl[int(clead[0]):, tgt] == 2).all(), \
            "control also never steps down — churn too weak, vacuous"


def test_attack_changes_trajectories():
    cfg = CFGS["raft"]
    on = simulator.run(dataclasses.replace(cfg, attack="elect"),
                       warmup=False)
    assert on.digest != run_cached(cfg).digest


def test_config_attack_surface():
    with pytest.raises(ValueError, match="attack"):
        Config(protocol="paxos", n_nodes=5, attack="elect")
    with pytest.raises(ValueError, match="tpu-engine"):
        Config(protocol="raft", engine="cpu", attack="elect")
    with pytest.raises(ValueError, match="attack_target"):
        Config(protocol="raft", n_nodes=5, attack="sticky",
               attack_target=7)
    with pytest.raises(ValueError, match="attack_rate"):
        Config(protocol="raft", n_nodes=5, attack_rate=0.5)
    # attack_target is read ONLY by 'sticky' — accepted-but-ignored
    # under 'elect' would break the reject-don't-ignore contract.
    with pytest.raises(ValueError, match="sticky"):
        Config(protocol="raft", n_nodes=5, attack="elect",
               attack_target=2)
    with pytest.raises(ValueError, match="miss_rate"):
        Config(protocol="raft", n_nodes=5, miss_rate=0.1)
    with pytest.raises(ValueError, match="max_delay_rounds"):
        Config(protocol="raft", n_nodes=5, max_delay_rounds=17)


def test_config_json_roundtrips_adversary_fields():
    cfg = dataclasses.replace(CFGS["dpos"], miss_rate=0.25,
                              max_delay_rounds=3)
    assert Config.from_json(cfg.to_json()) == cfg
    atk = Config(protocol="raft", n_nodes=5, attack="sticky",
                 attack_rate=0.7, attack_target=2)
    assert Config.from_json(atk.to_json()) == atk
    dsn = Config(protocol="hotstuff", f=2, n_nodes=7, desync_rate=0.15,
                 max_skew_rounds=4, view_timeout=4)
    assert Config.from_json(dsn.to_json()) == dsn
    # Pre-Appendix-A config dicts load with the library off.
    old = Config.from_json(json.dumps({"protocol": "dpos", "n_nodes": 24,
                                       "n_candidates": 12,
                                       "n_producers": 5}))
    assert old.miss_rate == 0.0 and old.max_delay_rounds == 0 \
        and old.attack == "none"
    # Pre-SPEC-B config dicts load with the synchronizer in sync path.
    pre_b = Config.from_json(json.dumps({"protocol": "pbft", "f": 2,
                                         "n_nodes": 7}))
    assert pre_b.desync_rate == 0.0 and pre_b.max_skew_rounds == 1


# --- 4. DPoS forks / LIB under gaps (SPEC §A.1 + §7) ------------------------

def _lib_brute(chain_p, n, n_producers):
    """Independent SPEC §7 LIB: largest k with >= T distinct producers
    among blocks k+1..n-1; -1 when none."""
    T = (2 * n_producers) // 3 + 1
    for k in range(n - 1, -1, -1):
        if len(set(int(p) for p in chain_p[k + 1:n])) >= T:
            return k
    # k = -1 is "blocks after -1" = the whole chain; lib_index's closed
    # form returns max(last_T - 1, -1), which is -1 iff even the whole
    # chain lacks T distinct producers... except when the FULL chain has
    # exactly T distinct and the T-th distinct appears at index 0.
    if len(set(int(p) for p in chain_p[:n])) >= T:
        return -1  # unreachable: the k = 0 case above would have won
    return -1


@pytest.mark.parametrize("seed", [1, 7, 23])
def test_lib_index_matches_brute_force_on_gappy_schedules(seed):
    cfg = dataclasses.replace(CFGS["dpos"], seed=seed, miss_rate=0.35,
                              n_rounds=64, n_sweeps=2)
    res = simulator.run(cfg, warmup=False)
    lib = lib_index(res.rec_b, res.counts, cfg.n_candidates,
                    cfg.n_producers)
    for b in range(cfg.n_sweeps):
        for v in range(0, cfg.n_nodes, 5):
            want = _lib_brute(res.rec_b[b, v], int(res.counts[b, v]),
                              cfg.n_producers)
            assert lib[b, v] == want, (b, v)


def test_miss_rate_makes_chains_gappy():
    """A chain-wide gap: some round produced in the miss-free run is
    missing from EVERY validator's chain under miss_rate > 0 — the
    fork-reachability precondition (validators now hold different
    subsequences of a sparser global chain)."""
    base = dataclasses.replace(CFGS["dpos"], n_rounds=64)
    plain = run_cached(base)
    miss = simulator.run(dataclasses.replace(base, miss_rate=0.35),
                         warmup=False)

    def rounds_of(res, b):
        out = set()
        for v in range(res.counts.shape[1]):
            out |= {int(r) for r in res.rec_a[b, v, :res.counts[b, v]]}
        return out

    gaps = rounds_of(plain, 0) - rounds_of(miss, 0)
    assert gaps, "miss_rate removed no slot chain-wide"
    # ...and validators genuinely diverge (different subsequences).
    lens = {int(c) for c in miss.counts[0]}
    assert len(lens) > 1, "all chains identical — drops too weak"


def test_lib_stalls_when_third_of_producers_miss():
    """The SPEC §7 rule T = 2K/3+1 needs all but K - T = K/3 - ish
    producers alive in a suffix: craft chains whose suffix holds only
    T - 1 distinct producers (> 1/3 of the set missing) and LIB must
    pin at the last T-distinct point, not the head."""
    K = 6                      # T = 5; 2 missing producers > K/3
    T = (2 * K) // 3 + 1
    assert T == 5
    L = 32
    # Blocks 0..15 rotate all 6 producers; 16..31 only producers 0-3.
    chain = np.array([k % K for k in range(16)]
                     + [k % (T - 1) for k in range(16)], np.int64)
    lib = lib_index(chain[None, :], np.array([L]), K, K)[0]
    brute = _lib_brute(chain, L, K)
    assert lib == brute
    # The suffix after any k >= 12 lacks 5 distinct producers, so LIB
    # stalls strictly below the gap point — far from the head.
    assert lib < 16 - 1, f"LIB {lib} advanced past the producer outage"
    # Control: the full-rotation chain is irreversible right up to the
    # last index with a T-deep distinct suffix.
    full = np.array([k % K for k in range(L)], np.int64)
    assert lib_index(full[None, :], np.array([L]), K, K)[0] == L - T - 1
    # End-to-end saturation: miss_rate = 1 kills every slot -> empty
    # chains, LIB = -1 (total stall).
    dead = simulator.run(dataclasses.replace(CFGS["dpos"], miss_rate=1.0),
                         warmup=False)
    assert dead.counts.sum() == 0
    assert (dead.extras["lib"] == -1).all()


# --- 5. scenarios, supervisor, checkpoints, CLI -----------------------------

SCENARIO_SHAPES = {
    "repeated-election-disruption": Config(
        protocol="raft", n_nodes=7, n_rounds=96, log_capacity=32,
        max_entries=24, n_sweeps=2, seed=11),
    "rolling-producer-outage": Config(
        protocol="dpos", n_nodes=24, n_rounds=96, log_capacity=96,
        n_candidates=12, n_producers=6, n_sweeps=2, seed=11),
    "delay-storm": Config(
        protocol="raft", n_nodes=7, n_rounds=96, log_capacity=32,
        max_entries=24, n_sweeps=2, seed=11),
    "crash-churn-under-partition": Config(
        protocol="pbft", f=2, n_nodes=7, n_rounds=96, log_capacity=16,
        n_sweeps=2, seed=11),
    "chained-commit-stall": Config(
        protocol="hotstuff", f=2, n_nodes=7, n_rounds=96,
        log_capacity=96, n_sweeps=2, seed=11),
    "stale-aggregator-inconsistency": Config(
        protocol="hotstuff", f=2, n_nodes=7, n_rounds=96,
        log_capacity=96, n_sweeps=2, seed=11),
    "view-desync-storm": Config(
        protocol="hotstuff", f=2, n_nodes=7, n_rounds=96,
        log_capacity=96, n_sweeps=2, seed=11),
    # advsearch-discovered (tools/advsearch, scenarios/discovered.json):
    # the search's low-drop compound collapse — same tuned shape the
    # distiller verified at.
    "discovered-compound-quorum-starvation": Config(
        protocol="raft", n_nodes=7, n_rounds=96, log_capacity=128,
        max_entries=96, n_sweeps=2, seed=11),
    # the §7c/§9b silent safety break: poisoned aggregator + lying
    # uplinks fork hotstuff QCs at availability 1.0 — tuned shape from
    # the hotstuff-forked-qc space, promoted across seeds 11/23/37.
    "discovered-silent-qc-fork": Config(
        protocol="hotstuff", f=2, n_nodes=7, n_rounds=96,
        log_capacity=96, view_timeout=4, n_sweeps=2, seed=11),
    # the SPEC §B compound collapse from the hotstuff-view-desync
    # space: timer skew + heavy drops kill commits outright (promoted
    # across seeds 11/23/37).
    "discovered-desync-commit-collapse": Config(
        protocol="hotstuff", f=2, n_nodes=7, n_rounds=96,
        log_capacity=96, view_timeout=4, n_sweeps=2, seed=11),
}


@pytest.mark.parametrize("name", sorted(scenarios.SCENARIOS))
def test_scenario_assertions_pass(name):
    """Every shipped scenario passes its own timeline assertions — the
    acceptance criterion's 'at least 3 scripted scenarios pass their
    availability-dip + bounded-recovery assertions in-test'."""
    cfg = scenarios.apply(SCENARIO_SHAPES[name], scenarios.get(name))
    res = simulator.run(cfg, warmup=False, telemetry=True, stats={})
    verdict = scenarios.evaluate(scenarios.get(name), res)
    assert verdict["passed"], verdict["checks"]
    # The verdict block is schema-valid for the CLI-report tripwire.
    from tools.validate_trace import (SCENARIO_CHECK_FIELDS,
                                      SCENARIO_REPORT_FIELDS)
    assert SCENARIO_REPORT_FIELDS <= set(verdict)
    for c in verdict["checks"].values():
        assert set(c) == SCENARIO_CHECK_FIELDS


def test_scenario_shapes_cover_all():
    assert set(SCENARIO_SHAPES) == set(scenarios.SCENARIOS)
    assert len(scenarios.SCENARIOS) >= 3
    # Each scenario's declared `tuned` reference shape IS the shape the
    # passing test above runs at — the declaration can't drift from the
    # evidence (and off_tuned() is empty exactly there).
    for name, s in scenarios.SCENARIOS.items():
        assert s.tuned, f"{name} declares no tuned shape"
        assert scenarios.off_tuned(s, SCENARIO_SHAPES[name]) == {}


def test_scenario_off_tuned_reports_shape_drift():
    s = scenarios.get("rolling-producer-outage")
    cfg = dataclasses.replace(SCENARIO_SHAPES[s.name], n_producers=4)
    assert scenarios.off_tuned(s, cfg) == {"n_producers": (4, 6)}


def test_scenario_protocol_switch_geometry():
    """A scenario that switches protocol re-derives the target
    protocol's population geometry from the base config — and REJECTS
    the switch when that would discard an explicitly-set field."""
    raft_base = SCENARIO_SHAPES["delay-storm"]
    # raft -> pbft: n_nodes re-derived from f (default f=1 -> 4 nodes).
    pbft = scenarios.apply(raft_base,
                           scenarios.get("crash-churn-under-partition"))
    assert pbft.protocol == "pbft" and pbft.n_nodes == 3 * raft_base.f + 1
    # ...but an explicit n_nodes the derivation would discard is loud.
    with pytest.raises(ValueError, match="discard n_nodes=7"):
        scenarios.apply(raft_base,
                        scenarios.get("crash-churn-under-partition"),
                        explicit={"n_nodes"})
    # raft(7 nodes) -> dpos: candidates/producers (defaults 16/4) are
    # clamped into the population instead of tripping Config's
    # K<=C<=V validation with fields the user never set.
    dpos = scenarios.apply(raft_base,
                           scenarios.get("rolling-producer-outage"))
    assert dpos.protocol == "dpos" and dpos.n_nodes == raft_base.n_nodes
    assert dpos.n_candidates == 7 and dpos.n_producers == 4
    # Explicit-and-consistent values pass through the clash check.
    ok = scenarios.apply(dataclasses.replace(raft_base, n_nodes=4),
                         scenarios.get("crash-churn-under-partition"),
                         explicit={"n_nodes", "f"})
    assert ok.n_nodes == 4
    # An explicitly requested CONFLICTING protocol is itself rejected,
    # not silently overridden by the scenario's forced protocol.
    with pytest.raises(ValueError, match="contradicting"):
        scenarios.apply(raft_base, scenarios.get("rolling-producer-outage"),
                        explicit={"protocol"})
    # ...while an explicit MATCHING protocol is fine (no switch at all).
    same = scenarios.apply(raft_base, scenarios.get("delay-storm"),
                           explicit={"protocol"})
    assert same.protocol == "raft"


def test_scenario_rejects_short_runs():
    with pytest.raises(ValueError, match="n_rounds"):
        scenarios.apply(dataclasses.replace(
            SCENARIO_SHAPES["delay-storm"], n_rounds=8),
            scenarios.get("delay-storm"))


def test_scenario_unknown_name():
    # ValueError, not KeyError: str(KeyError(msg)) is repr(msg), which
    # would leak quoting into parser.error's user-facing message.
    with pytest.raises(ValueError, match="known"):
        scenarios.get("byzantine-apocalypse")


def test_supervisor_fallback_degrades_crash_config():
    """A crashing run may now degrade to the oracle (the §6c mirror):
    after an injected failure exhausts retries, the fallback result is
    byte-identical to both engines' direct runs."""
    cfg = dataclasses.replace(CFGS["raft"], **CRASH)
    faults.install(transient_dispatches=(1,))
    try:
        res = supervisor.supervised_run(cfg, retries=0, fallback_cpu=True,
                                        backoff_s=0.0)
    finally:
        faults.reset()
    assert res.extras["run_report"]["fallback_used"]
    assert res.payload == run_cached(cfg).payload


def test_supervisor_rejects_fallback_cpu_with_attack():
    """The one remaining TPU-only adversary dies loudly at supervision
    SETUP — not via Config's engine='cpu' rejection mid-degradation."""
    cfg = dataclasses.replace(CFGS["raft"], attack="elect")
    with pytest.raises(ValueError, match="attack"):
        supervisor.supervised_run(cfg, fallback_cpu=True)


def test_adversary_checkpoint_resume_bit_identical(tmp_path):
    """Snapshot/resume under an active scenario-class config (miss +
    crash + delay) reproduces the uninterrupted digest — no adversary
    state beyond the down mask rides the carry, and the draws are pure
    counter functions."""
    cfg = dataclasses.replace(CFGS["dpos"], miss_rate=0.3, scan_chunk=8,
                              **CRASH, **DELAY)
    base = simulator.run(cfg, warmup=False)
    ck = tmp_path / "ck.npz"
    eng = simulator.engine_def(cfg)
    seeds = jnp.asarray(runner.make_seeds(cfg))
    carry = runner._init_jit(cfg, eng, seeds)
    carry = runner._chunk_jit(cfg, eng, 16, carry, jnp.int32(0))
    runner.save_checkpoint(ck, cfg, carry, 16)
    resumed = simulator.run(cfg, warmup=False, checkpoint_path=str(ck),
                            resume=True, stats=(stats := {}))
    assert stats["start_round"] == 16
    assert resumed.payload == base.payload


def test_adversary_knobs_are_snapshot_identity(tmp_path):
    """A snapshot written WITHOUT the adversary must not be resumed by
    a run WITH it (the trajectories differ from round 0): the loader
    skips it as a config mismatch and the run restarts fresh —
    loudly correct, never silently wrong."""
    plain = dataclasses.replace(CFGS["dpos"], scan_chunk=8)
    ck = tmp_path / "ck.npz"
    eng = simulator.engine_def(plain)
    seeds = jnp.asarray(runner.make_seeds(plain))
    carry = runner._init_jit(plain, eng, seeds)
    carry = runner._chunk_jit(plain, eng, 16, carry, jnp.int32(0))
    runner.save_checkpoint(ck, plain, carry, 16)
    adv = dataclasses.replace(plain, miss_rate=0.3)
    res = simulator.run(adv, warmup=False, checkpoint_path=str(ck),
                        resume=True, stats=(stats := {}))
    assert stats["start_round"] == 0, \
        "a pre-adversary snapshot was resumed into an adversarial run"
    assert res.payload == simulator.run(
        dataclasses.replace(adv, scan_chunk=0), warmup=False).payload


def _run_native(flags):
    subprocess.run(["make", "-C", str(CPP_DIR), "-s", "consensus-sim"],
                   check=True)
    out = subprocess.run([str(CPP_DIR / "consensus-sim"), *flags],
                         check=True, capture_output=True, text=True)
    return json.loads(out.stdout)


def test_native_cli_adversary_flags_match_tpu():
    """The new native flags (--crash-prob/--recover-prob/--max-crashed/
    --miss-rate/--max-delay-rounds) drive the same trajectories as the
    Python front door's TPU engine."""
    flags = ["--protocol", "dpos", "--nodes", "24", "--rounds", "48",
             "--log-capacity", "64", "--candidates", "12",
             "--producers", "5", "--epoch-len", "8", "--seed", "5",
             "--drop-rate", "0.3", "--miss-rate", "0.3",
             "--max-delay-rounds", "4", "--crash-prob", "0.15",
             "--recover-prob", "0.3", "--max-crashed", "3"]
    native = _run_native(flags)
    cfg = dataclasses.replace(CFGS["dpos"], miss_rate=0.3,
                              max_delay_rounds=4, **CRASH)
    assert native["digest"] == run_cached(cfg).digest


def test_native_cli_rejects_cpu_scenario_and_bad_miss():
    subprocess.run(["make", "-C", str(CPP_DIR), "-s", "consensus-sim"],
                   check=True)
    sim = str(CPP_DIR / "consensus-sim")
    r = subprocess.run([sim, "--protocol", "raft", "--scenario",
                        "delay-storm"], capture_output=True, text=True)
    assert r.returncode != 0 and "tpu" in r.stderr
    r = subprocess.run([sim, "--protocol", "raft", "--miss-rate", "0.2"],
                       capture_output=True, text=True)
    assert r.returncode != 0 and "DPoS" in r.stderr


def test_python_cli_scenario_verdict(capsys):
    """--scenario through the Python front door: verdict in the report,
    exit code reflects the assertions. Runs the EXACT `make check`
    smoke invocation (tools/check.SCENARIO_SMOKE) so the CI gate and
    this test cannot drift apart — and so the smoke provably runs at
    delay-storm's tuned reference shape."""
    from consensus_tpu import cli
    from consensus_tpu import scenarios
    from tools.check import SCENARIO_SMOKE
    argv = SCENARIO_SMOKE[SCENARIO_SMOKE.index("--scenario"):]
    smoke_cfg = scenarios.apply(
        cli.args_to_config(cli.build_parser().parse_args(argv)),
        scenarios.get("delay-storm"))
    assert scenarios.off_tuned(scenarios.get("delay-storm"),
                               smoke_cfg) == {}
    rc = cli.main(argv)
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert out["scenario"]["name"] == "delay-storm"
    assert out["scenario"]["passed"] is True
    assert out["telemetry"]["attack_rounds"] == 0


def test_python_cli_hotstuff_smoke_verdict(capsys):
    """The second `make check` scenario smoke (tools/check
    .HOTSTUFF_SMOKE): the EXACT CI invocation of the chained-commit
    stall runs at the scenario's tuned reference shape and passes its
    bounds — same drift guard as test_python_cli_scenario_verdict."""
    from consensus_tpu import cli
    from consensus_tpu import scenarios
    from tools.check import HOTSTUFF_SMOKE
    argv = HOTSTUFF_SMOKE[HOTSTUFF_SMOKE.index("--scenario"):]
    smoke_cfg = scenarios.apply(
        cli.args_to_config(cli.build_parser().parse_args(argv)),
        scenarios.get("chained-commit-stall"))
    assert scenarios.off_tuned(scenarios.get("chained-commit-stall"),
                               smoke_cfg) == {}
    rc = cli.main(argv)
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert out["scenario"]["name"] == "chained-commit-stall"
    assert out["scenario"]["passed"] is True
    # The stall shape is real: failed views observed (timeout-driven
    # view changes) while commits still flow.
    assert out["telemetry"]["view_changes"] > 0
    assert out["telemetry"]["commits_learned"] > 0


def test_python_cli_desync_smoke_verdict(capsys):
    """The SPEC §B `make check` smoke (tools/check.DESYNC_SMOKE): the
    EXACT CI invocation of the view-desync storm runs at the scenario's
    tuned reference shape and passes its bounds — and the synchronizer
    telemetry is live in the CLI report (views genuinely spread)."""
    from consensus_tpu import cli
    from consensus_tpu import scenarios
    from tools.check import DESYNC_SMOKE
    argv = DESYNC_SMOKE[DESYNC_SMOKE.index("--scenario"):]
    smoke_cfg = scenarios.apply(
        cli.args_to_config(cli.build_parser().parse_args(argv)),
        scenarios.get("view-desync-storm"))
    assert scenarios.off_tuned(scenarios.get("view-desync-storm"),
                               smoke_cfg) == {}
    rc = cli.main(argv)
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert out["scenario"]["name"] == "view-desync-storm"
    assert out["scenario"]["passed"] is True
    assert out["telemetry"]["view_spread_max"] > 0
    assert out["telemetry"]["desync_rounds"] > 0
    assert out["telemetry"]["sync_msgs_delivered"] > 0
    assert out["telemetry"]["safety_violations"] == 0


def test_python_cli_rejects_cpu_scenario():
    from consensus_tpu import cli
    with pytest.raises(SystemExit):
        cli.main(["--scenario", "delay-storm", "--engine", "cpu",
                  "--protocol", "raft"])


# --- slow tier: SIGKILL-resume under an active scenario ---------------------

@pytest.mark.slow
def test_sigkill_midrun_under_scenario_is_bit_identical(tmp_path):
    """Satellite acceptance: a checkpointed CLI scenario run (attack
    knobs + flight recorder both riding the snapshot) is SIGKILLed by
    the fault harness after chunk 2; the resumed run reproduces the
    uninterrupted digest bit-for-bit."""
    import os
    import signal
    import sys

    ck = tmp_path / "ck.npz"
    flags = ["--scenario", "rolling-producer-outage", "--protocol", "dpos",
             "--nodes", "24", "--rounds", "96", "--log-capacity", "96",
             "--candidates", "12", "--producers", "6", "--sweeps", "2",
             "--seed", "11", "--scan-chunk", "8", "--platform", "cpu",
             "--checkpoint", str(ck)]
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               **{faults.ENV_VAR: json.dumps({"kill_after_chunk": 2})})
    p = subprocess.run([sys.executable, "-m", "consensus_tpu"] + flags,
                       capture_output=True, text=True, env=env,
                       cwd=pathlib.Path(__file__).resolve().parents[1],
                       timeout=600)
    assert p.returncode == -signal.SIGKILL, (p.returncode, p.stderr)

    cfg = dataclasses.replace(
        scenarios.apply(SCENARIO_SHAPES["rolling-producer-outage"],
                        scenarios.get("rolling-producer-outage")),
        scan_chunk=8)
    assert runner.peek_checkpoint(ck, cfg) == 16
    base = simulator.run(cfg, warmup=False, telemetry=True, stats={})
    res = simulator.run(cfg, warmup=False, telemetry=True,
                        checkpoint_path=str(ck), resume=True,
                        stats=(stats := {}))
    assert stats["start_round"] == 16
    assert res.payload == base.payload
    # The resumed run's flight series judges the scenario identically.
    v_base = scenarios.evaluate(
        scenarios.get("rolling-producer-outage"), base)
    v_res = scenarios.evaluate(
        scenarios.get("rolling-producer-outage"), res)
    assert v_base == v_res and v_res["passed"]
