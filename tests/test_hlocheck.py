"""hlo-contract: the compiled-program static-analysis layer
(tools/hlocheck — docs/STATIC_ANALYSIS.md "compiled-program layer").

Three responsibilities, mirroring tests/test_static_analysis.py's
pattern for the AST layer:

  1. the CLEAN-REPO assertion: every registered (engine × flagship
     shape × mesh) target passes all five contracts, and the committed
     fingerprints under benchmarks/parts/fingerprints/ match what this
     toolchain lowers today (the full gate, in-process);
  2. SEEDED VIOLATIONS: each contract fires against a fixture engine
     compiled through the production lowering path
     (tests/fixtures/hlocheck/bad_engines.py) — an injected f64
     promotion, a full-carry all-gather, a host pure_callback, a
     sort-budget overrun, an un-donated carry;
  3. FINGERPRINT semantics: mesh reshape (2,4)→(1,8) keeps verdicts
     identical, --update round-trips byte-stable, and the
     compiler-version tolerance policy (same-toolchain structural
     drift fails, cross-toolchain drift warns, verdict drift always
     fails).
"""
import copy
import json
import os
import pathlib
import sys

import jax
import pytest

sys.path.insert(0, str(pathlib.Path(__file__).parent))
sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from fixtures.hlocheck import bad_engines  # noqa: E402
from tools.hlocheck import __main__ as hlocheck_main  # noqa: E402
from tools.hlocheck import contracts, fingerprint, hlo, registry  # noqa: E402

FAKE_CONTRACT = contracts.EngineContract(
    engine="fake", sort_budget=1, cumsum_budget=2, node_sharded="strict")


def _violations(eng, mesh_shape=None, *, mode=None, axis=None,
                jit_fn=None, contract=FAKE_CONTRACT):
    cfg = bad_engines.CFG
    rep = hlo.compiled_report(cfg, eng, mesh_shape, jit_fn=jit_fn)
    return contracts.check_module(
        rep, contract, cfg, mode=mode, axis=axis,
        carry_leaves=hlo.n_carry_leaves(cfg, eng),
        enforce_budgets=mesh_shape is None)


def _contracts_hit(viols):
    return {v.contract for v in viols}


# --- 1. clean repo -----------------------------------------------------------

@pytest.mark.skipif(
    os.environ.get("CONSENSUS_HLO_LAYER_RAN") == "1",
    reason="the check.py hlo layer already ran the full gate in this "
           "`make check` invocation — don't lower all 8 targets twice")
def test_full_gate_green_and_fingerprints_match():
    """`python -m tools.hlocheck` (in-process): every registered target
    passes every contract AND matches its committed fingerprint. This is
    the tier-1 mirror of the check.py `hlo` layer (skipped under `make
    check`, which runs the identical gate as its own layer first)."""
    assert hlocheck_main.run_checks() == 0


def test_every_flagship_config_has_a_committed_fingerprint():
    from benchmarks.run_benchmarks import CONFIGS
    names = {t.name for t in registry.targets()}
    assert set(CONFIGS) <= names, "flagship config missing from registry"
    for name in CONFIGS:
        doc = fingerprint.load(name)
        assert doc is not None, f"no committed fingerprint for {name}"
        assert doc["schema"] == fingerprint.SCHEMA
        for key, var in doc["variants"].items():
            assert set(var["verdicts"].values()) == {"pass"}, (name, key)
            # The donation satellite, statically: every carry buffer of
            # every flagship program aliases an output.
            assert var["donated_leaves"] == var["carry_leaves"] > 0


def test_negative_control_fixture_passes_production_path():
    # ok_engine through the production jit: all five contracts pass —
    # so each bad fixture below isolates exactly its seeded violation.
    assert _violations(bad_engines.ok_engine) == []


# --- 2. seeded violations ----------------------------------------------------

def test_injected_f64_promotion_fires_dtypes():
    with jax.experimental.enable_x64(True):
        viols = _violations(bad_engines.f64_engine)
    assert "dtypes" in _contracts_hit(viols)
    assert any("f64" in v.message for v in viols)
    # Without the x64 flag the same source canonicalizes to f32 and the
    # program is clean — the checker sees the COMPILED truth either way.
    assert "dtypes" not in _contracts_hit(
        _violations(bad_engines.f64_engine))


def test_full_carry_all_gather_fires_collectives():
    viols = _violations(bad_engines.gather_engine, (2, 4),
                        mode="strict", axis="node")
    assert "collectives" in _contracts_hit(viols)
    assert any("full-carry" in v.message or "N, L" in v.message
               for v in viols)
    # The same violation also breaks the weaker "bounded" claim: a full
    # [N, L] leaf is never O(N) metadata.
    viols_b = _violations(bad_engines.gather_engine, (2, 4),
                          mode="bounded", axis="node")
    assert "collectives" in _contracts_hit(viols_b)


def test_host_pure_callback_fires_host_boundary():
    viols = _violations(bad_engines.callback_engine)
    assert "host_boundary" in _contracts_hit(viols)
    assert any("callback" in v.message for v in viols)


def test_sort_budget_overrun_fires():
    viols = _violations(bad_engines.sorty_engine)
    assert "sort_budget" in _contracts_hit(viols)
    # 2 sorts > budget 1, named in the message with the budget value.
    assert any("> budget 1" in v.message for v in viols)


def test_retired_tally_round_exceeds_new_lowered_ceilings():
    """The sort-diet regression gate bites at its NEW level: the
    retired pre-diet round (3 sorts + the cumsum/cummax/cummin
    brackets, tests/reference_pbft_bcast.py) compiled through the
    PRODUCTION chunk jit at the flagship shape violates the LOWERED
    pbft-bcast ceilings (sort_budget 1, cumsum_budget 20) — proving the
    tightened ceiling fires on precisely the program it retired, not
    just on the old 3/33 one."""
    from reference_pbft_bcast import reference_engine

    from benchmarks.run_benchmarks import CONFIGS

    cfg = CONFIGS["pbft-100k-bcast"]
    eng = reference_engine()
    rep = hlo.compiled_report(cfg, eng)
    assert rep.sort_ops == 3 and rep.cumsum_ops > 20
    con = contracts.program_contracts()["pbft-bcast"]
    assert con.sort_budget == 1 and con.cumsum_budget == 20
    viols = contracts.check_module(
        rep, con, cfg, mode=None, axis=None,
        carry_leaves=hlo.n_carry_leaves(cfg, eng))
    assert _contracts_hit(viols) == {"sort_budget"}
    assert any("3 sort-class ops > budget 1" in v.message for v in viols)
    assert any("> budget 20" in v.message for v in viols)


def test_strided_reduce_windows_not_counted_as_cumsum():
    """The classifier refinement behind the lowered ceilings: plain
    reductions lower on CPU as TILED reduce-window cascades
    (stride > 1) and must land in the reduce class; only unit-stride
    prefix-scan windows count against the cumsum budget."""
    import jax
    import jax.numpy as jnp

    x = jax.ShapeDtypeStruct((16, 100000), jnp.int32)
    scan = hlo.analyze(jax.jit(
        lambda a: jnp.cumsum(a, axis=1)).lower(x).compile().as_text())
    red = hlo.analyze(jax.jit(
        lambda a: jnp.sum(a, axis=1)).lower(x).compile().as_text())
    assert scan.cumsum_ops > 0
    assert red.cumsum_ops == 0
    assert red.ops.get("reduce-window-strided", 0) > 0


def test_fsweep_target_contract_pinned():
    """The pbft-100k-bcast-fsweep registry entry lowers the EXACT
    one-program padded ladder `--fault-model bcast --f-sweep`
    dispatches and holds it to the pbft-bcast ceilings (one sort per
    round, scan brackets within budget, no collectives, no host
    boundary) at the flagship N_pad = 100k shape."""
    tgt = registry.target("pbft-100k-bcast-fsweep")
    assert tgt.fsweep and 3 * max(tgt.fsweep) + 1 == 100_000
    rep = hlo.fsweep_compiled_report(tgt.cfg, tgt.fsweep)
    con = contracts.program_contracts()["pbft-bcast"]
    viols = contracts.check_module(rep, con, tgt.cfg, mode=None,
                                   axis=None, carry_leaves=0)
    assert viols == []
    assert rep.sort_ops == 1


def test_hotstuff_zero_ceiling_fires_on_seeded_sort_and_cumsum():
    """The linear-BFT contract bites at ZERO: the real hotstuff round
    with one bolted-on sort and one cumsum, compiled through the
    production chunk jit at the canonical hotstuff-1k shape, violates
    hotstuff's OWN declared 0/0 budgets — proving the dpos-class
    ceiling fires on the first sort-class op, not after a grace
    allowance."""
    tgt = registry.target("hotstuff-1k")
    eng = bad_engines.sorty_hotstuff_engine()
    rep = hlo.compiled_report(tgt.cfg, eng)
    assert rep.sort_ops >= 1 and rep.cumsum_ops >= 1
    con = contracts.program_contracts()["hotstuff"]
    assert con.sort_budget == 0 and con.cumsum_budget == 0
    viols = contracts.check_module(
        rep, con, tgt.cfg, mode=None, axis=None,
        carry_leaves=hlo.n_carry_leaves(tgt.cfg, eng))
    assert _contracts_hit(viols) == {"sort_budget"}
    assert any("> budget 0" in v.message for v in viols)
    # And the unmodified engine is the negative control: clean at 0/0.
    from consensus_tpu.network import simulator
    clean = hlo.compiled_report(tgt.cfg, simulator.engine_def(tgt.cfg))
    assert clean.sort_ops == 0 and clean.cumsum_ops == 0


def test_undonated_carry_fires_donation():
    viols = _violations(bad_engines.ok_engine,
                        jit_fn=bad_engines.undonated_chunk)
    assert _contracts_hit(viols) == {"donation"}
    assert any("0/2" in v.message for v in viols)


def test_sweep_only_mesh_must_be_collective_free():
    # The universal sweep-axis invariant, violated: gather_engine's
    # permutation is node-local per sweep, so a sweep-only mesh is
    # clean — but checked at mode "zero" a node-sharded gather program
    # is not. (Guards the mode plumbing, not the engine.)
    viols = _violations(bad_engines.gather_engine, (2, 4),
                        mode="zero", axis="node")
    assert "collectives" in _contracts_hit(viols)
    assert _violations(bad_engines.gather_engine, (2,),
                       mode="zero", axis="sweep") == []


def test_registry_mode_stronger_than_engine_claim_rejected():
    con = contracts.EngineContract(engine="fake", sort_budget=9,
                                   cumsum_budget=9, node_sharded=None)
    viols = _violations(bad_engines.ok_engine, (2, 4), mode="strict",
                        axis="node", contract=con)
    assert any("claims node_sharded=None" in v.message for v in viols)


# --- 3. fingerprint semantics ------------------------------------------------

def test_mesh_reshape_keeps_verdicts_identical():
    """(2,4) → (1,8) on the canonical capped-raft target: shard sizes
    change, contract verdicts may not (the satellite's stability
    claim)."""
    tgt = registry.target("raft-1k-cap8")
    from consensus_tpu.network import simulator
    eng = simulator.engine_def(tgt.cfg)
    con = contracts.program_contracts()[eng.name]
    leaves = hlo.n_carry_leaves(tgt.cfg, eng)
    verd = {}
    for shape in ((2, 4), (1, 8)):
        rep = hlo.compiled_report(tgt.cfg, eng, shape)
        viols = contracts.check_module(
            rep, con, tgt.cfg, mode="strict", axis="node",
            carry_leaves=leaves, enforce_budgets=False)
        verd[shape] = contracts.verdicts(viols)
    assert verd[(2, 4)] == verd[(1, 8)]
    assert set(verd[(2, 4)].values()) == {"pass"}


def test_update_roundtrips_byte_stable(tmp_path, monkeypatch):
    monkeypatch.setattr(registry, "FINGERPRINT_DIR", tmp_path)
    assert hlocheck_main.run_checks(only=["raft-1k-cap8"],
                                    update=True) == 0
    first = (tmp_path / "raft-1k-cap8.json").read_bytes()
    assert hlocheck_main.run_checks(only=["raft-1k-cap8"],
                                    update=True) == 0
    assert (tmp_path / "raft-1k-cap8.json").read_bytes() == first
    # And a freshly written fingerprint immediately verifies.
    assert hlocheck_main.run_checks(only=["raft-1k-cap8"]) == 0
    doc = json.loads(first)
    assert doc["name"] == "raft-1k-cap8" and doc["variants"]


def test_drift_policy_same_vs_cross_toolchain():
    committed = fingerprint.load("raft-1k-cap8")
    assert committed is not None
    current = copy.deepcopy(committed)
    # Structural mutation: histogram count bumps (a new fused pass).
    var = next(iter(current["variants"]))
    current["variants"][var]["histogram"]["elementwise"] = 99999
    verdict_diffs, struct_diffs = fingerprint.diff(committed, current)
    assert not verdict_diffs and struct_diffs
    assert any("99999" in line for line in struct_diffs)
    # Same recorded toolchain as the running one ⇒ hard failure branch.
    assert fingerprint.same_toolchain(committed)
    # A fingerprint recorded under another jaxlib ⇒ the warn branch.
    foreign = copy.deepcopy(committed)
    foreign["toolchain"] = {"jax": "9.9.9", "jaxlib": "9.9.9"}
    assert not fingerprint.same_toolchain(foreign)
    # Verdict mutation is caught separately and always fails.
    current2 = copy.deepcopy(committed)
    current2["variants"][var]["verdicts"]["donation"] = "fail"
    verdict_diffs2, _ = fingerprint.diff(committed, current2)
    assert verdict_diffs2


def test_cli_rejects_unknown_target_and_lists(capsys):
    assert hlocheck_main.run_checks(only=["no-such-target"]) == 2
    assert hlocheck_main.main(["--list"]) == 0
    out = capsys.readouterr().out
    assert "raft-100k" in out and "node2x4" in out


def test_update_refused_while_contracts_fail(tmp_path, monkeypatch):
    """--update must never commit a fingerprint for a violating program
    (the budget ceiling can only be raised by editing the engine's
    declaration, not by regenerating artifacts)."""
    from consensus_tpu.engines import pbft_bcast
    monkeypatch.setattr(registry, "FINGERPRINT_DIR", tmp_path)
    monkeypatch.setattr(
        pbft_bcast, "PROGRAM_CONTRACT",
        dict(pbft_bcast.PROGRAM_CONTRACT, sort_budget=0))
    rc = hlocheck_main.run_checks(only=["pbft-100k-bcast"], update=True)
    assert rc == 1
    assert not (tmp_path / "pbft-100k-bcast.json").exists()


def test_collective_census_library_matches_sizes():
    """The generalized compiled_collectives harness: tuple-typed
    collectives report their largest member and the capped-raft
    canonical shape stays within the O(N) metadata bound."""
    tgt = registry.target("raft-1k-cap8")
    colls = hlo.compiled_collectives(tgt.cfg, (2, 4))
    assert colls.get("all-reduce")
    n = tgt.cfg.n_nodes
    assert all(s <= 2 * n for s in colls.get("all-gather", []))
