"""Native-engine sanitizer gate (SURVEY.md §5 "race detection").

Builds the oracle + selftest with -fsanitize=address,undefined and runs
every protocol on adversarial configs twice (determinism check inside).
The Rust reference gets memory safety from its compiler; the C++ oracle
earns it here on every test run.
"""
import pathlib
import subprocess

CPP = pathlib.Path(__file__).resolve().parents[1] / "cpp"


def test_oracle_asan_ubsan_clean():
    out = subprocess.run(["make", "-C", str(CPP), "-s", "san-test"],
                         capture_output=True, text=True)
    assert out.returncode == 0, out.stdout + out.stderr
    assert "ALL CLEAN" in out.stdout
