"""Seeded-violation engines for the hlocheck contracts
(tests/test_hlocheck.py — the compiled-program sibling of
tests/fixtures/lint/'s AST fixture trees).

Each fake engine compiles a real program through the PRODUCTION lowering
path (tools/hlocheck/hlo.compiled_text over runner._chunk_jit) that
violates exactly one contract, proving the check fires on compiler
output, not on source patterns:

  * ``f64_engine``        — a float64 promotion (lowered under
    ``jax.experimental.enable_x64`` so the wide type survives jax's
    canonicalization, exactly how a real leak would arrive: an env
    flag flipping x64 on) → ``dtypes``;
  * ``gather_engine``     — a data-dependent global permutation of the
    [N, L] log under node sharding: GSPMD has no local rewrite, so it
    all-gathers the FULL carry leaf → ``collectives``;
  * ``callback_engine``   — a ``jax.pure_callback`` inside the round →
    ``host_boundary`` (custom-call to xla_python_cpu_callback);
  * ``sorty_engine``      — two payload sorts against a declared
    ``sort_budget=1`` → ``sort_budget``;
  * ``ok_engine`` + ``undonated_chunk`` — a clean round lowered through
    a jit twin WITHOUT ``donate_argnums`` → ``donation`` (and through
    the production jit it passes everything: the negative control).

``undonated_chunk`` doubles as the bit-identity REFERENCE for the
donation satellite (tests/test_donation.py): same scan semantics as
``runner._chunk_jit`` minus masking/telemetry/donation.
"""
from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from consensus_tpu.core.config import Config
from consensus_tpu.network.runner import EngineDef
from consensus_tpu.parallel.mesh import NODE_AXIS

CFG = Config(protocol="raft", n_nodes=32, n_rounds=4, n_sweeps=2,
             log_capacity=8, max_entries=4)


class FakeCarry(NamedTuple):
    vals: jnp.ndarray   # [N] u32
    log: jnp.ndarray    # [N, L] i32


def _make_carry(cfg: Config, seed) -> FakeCarry:
    n, ell = cfg.n_nodes, cfg.log_capacity
    return FakeCarry(
        vals=jnp.full((n,), seed, jnp.uint32)
        + jnp.arange(n, dtype=jnp.uint32),
        log=jnp.zeros((n, ell), jnp.int32))


def _pspec(cfg: Config) -> FakeCarry:
    return FakeCarry(vals=P(NODE_AXIS), log=P(NODE_AXIS, None))


def _extract(c: FakeCarry) -> dict:
    return {"vals": c.vals}


def _engine(round_fn, name: str) -> EngineDef:
    return EngineDef(name, _make_carry, round_fn, _extract, _pspec)


def _ok_round(cfg: Config, c: FakeCarry, r) -> FakeCarry:
    return FakeCarry(vals=c.vals + jnp.uint32(1), log=c.log + 1)


def _f64_round(cfg: Config, c: FakeCarry, r) -> FakeCarry:
    # Only widens when x64 is enabled — lower inside
    # jax.experimental.enable_x64(True), like the env-flag leak it seeds.
    wide = c.log.astype(jnp.float64) * 1.5
    return FakeCarry(vals=c.vals + jnp.uint32(1),
                     log=wide.astype(jnp.int32))


def _gather_round(cfg: Config, c: FakeCarry, r) -> FakeCarry:
    # Global data-dependent permutation: every shard needs every row, so
    # the partitioner all-gathers the full [N, L] leaf (the "bad
    # sharding annotation" failure class: the pspec promises node
    # sharding the computation then un-does).
    order = jnp.argsort(c.vals)
    return FakeCarry(vals=c.vals + jnp.uint32(1), log=c.log[order])


def _callback_round(cfg: Config, c: FakeCarry, r) -> FakeCarry:
    v = jax.pure_callback(
        lambda x: x, jax.ShapeDtypeStruct(c.vals.shape, c.vals.dtype),
        c.vals, vmap_method="sequential")
    return FakeCarry(vals=v + jnp.uint32(1), log=c.log + 1)


def _sorty_round(cfg: Config, c: FakeCarry, r) -> FakeCarry:
    s1 = jnp.sort(c.vals)
    s2 = jnp.sort(c.log, axis=0)
    return FakeCarry(vals=s1 + jnp.uint32(1), log=s2 + 1)


def sorty_hotstuff_engine() -> EngineDef:
    """The REAL hotstuff round with a gratuitous sort + cumsum bolted
    on — the regression a naive 'optimization' would introduce. Checked
    against hotstuff's OWN declared contract (sort_budget 0 /
    cumsum_budget 0), it proves the linear-BFT ceiling fires at zero:
    even one sort-class or one cumsum-class op in the compiled round is
    a violation (tests/test_hlocheck.py)."""
    from consensus_tpu.engines import hotstuff

    def bad_round(cfg: Config, st, r):
        new = hotstuff.hotstuff_round(cfg, st, r)
        return new._replace(view=jnp.sort(new.view),
                            timer=jnp.cumsum(new.timer))

    base = hotstuff.get_engine()
    return EngineDef("fake-hotstuff-sorty", base.make_carry, bad_round,
                     base.extract, base.carry_pspec)


ok_engine = _engine(_ok_round, "fake-ok")
f64_engine = _engine(_f64_round, "fake-f64")
gather_engine = _engine(_gather_round, "fake-gather")
callback_engine = _engine(_callback_round, "fake-callback")
sorty_engine = _engine(_sorty_round, "fake-sorty")


@functools.partial(jax.jit, static_argnums=(0, 1, 2),
                   static_argnames=("mesh",))
def undonated_chunk(cfg, eng, n_rounds, carry, r0, telem=None, *, mesh=None):
    """runner._chunk_jit minus donate_argnums (and minus the length-1
    masking / telemetry paths neither fixture needs): the un-donated
    carry seeded violation, and the donation bit-identity reference."""
    def body(c, r):
        return jax.vmap(lambda s: eng.round_fn(cfg, s, r))(c), None
    carry, _ = jax.lax.scan(body, carry,
                            r0 + jnp.arange(n_rounds, dtype=jnp.int32))
    return carry
