"""Seeded dtype-discipline violations (tests/test_static_analysis.py):
a 64-bit dtype literal and dtype-defaulted constructors in device
scope. Never imported — AST fixture only."""
import jax.numpy as jnp


def fake_init(n: int):
    a = jnp.zeros(n)                    # dtype-defaulted constructor
    b = jnp.arange(n)                   # dtype-defaulted constructor
    c = jnp.asarray([1, 2, 3])          # literal without a stated width
    d = jnp.zeros((n, n), jnp.int64)    # 64-bit dtype
    return a, b, c, d


class FakeTable:
    K = jnp.ones(4)                     # class-level defaulted constructor
