"""Seeded registry-sync violations: a telemetry counter unknown to the
validator, and a CRASH_SPLIT that declares `timer` persistent while the
round's recovery code resets it. Never imported — AST fixture only."""
from typing import NamedTuple

import jax.numpy as jnp

from ..ops.adversary import CRASH_TELEMETRY, crash_transition, freeze_down

FAKE_TELEMETRY = ("good_counter", "rogue_counter") + CRASH_TELEMETRY

# Latency-registry drift: 'rogue_hist' is unknown to the validator's
# LATENCY_HISTOGRAMS and its 'stale_hist' is recorded by no engine.
FAKE_LATENCY = ("good_hist", "rogue_hist")


class FakeState(NamedTuple):
    seed: object
    term: object
    timer: object
    down: object


CRASH_SPLIT = {
    "seed": "meta",
    "term": "persistent",
    "timer": "persistent",   # WRONG: fake_round resets it on `rec`
    "down": "meta",
}


def fake_round(cfg, st, r):
    down, rec, crashed = crash_transition(st.seed, r, st.down, 1, 1, 0)
    term, timer = st.term, st.timer
    timer = jnp.where(rec, 0, timer)
    frozen = (term, timer)
    term, timer = freeze_down(down, frozen, (term, timer))
    return FakeState(st.seed, term, timer, down)
