"""Fixture crash-telemetry tail. Never imported — AST fixture only."""
CRASH_TELEMETRY = ("crashes",)


def crash_transition(seed, r, down, crash_cut: int, recover_cut: int,
                     max_crashed: int):
    return down, down, down


def freeze_down(down, frozen, new_leaves):
    return new_leaves
