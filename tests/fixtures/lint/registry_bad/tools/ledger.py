"""Fixture producer: 'rogue_row_field' is missing from the validator's
LEDGER_ROW_FIELDS, whose 'stale_row_field' no producer emits."""
ROW_FIELDS = ("source", "rogue_row_field")
