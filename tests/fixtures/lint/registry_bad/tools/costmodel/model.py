"""Fixture producer: 'rogue_card_field' is missing from the validator's
COST_CARD_FIELDS, whose 'stale_card_field' no producer emits."""
CARD_FIELDS = ("schema", "rogue_card_field")
