"""Fixture validator registry: 'stale_counter' is reported by no engine
and the engine's 'rogue_counter' is missing here."""
TELEMETRY_COUNTERS = frozenset({
    "good_counter", "stale_counter", "crashes",
})
