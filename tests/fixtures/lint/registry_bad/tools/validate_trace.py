"""Fixture validator registry: 'stale_counter' is reported by no engine
and the engine's 'rogue_counter' is missing here — and the same pair of
drifts seeded for the flight-recorder latency registry."""
TELEMETRY_COUNTERS = frozenset({
    "good_counter", "stale_counter", "crashes",
})
LATENCY_HISTOGRAMS = frozenset({
    "good_hist", "stale_hist",
})
