"""Fixture validator registry: 'stale_counter' is reported by no engine
and the engine's 'rogue_counter' is missing here — and the same pair of
drifts seeded for the flight-recorder latency registry."""
TELEMETRY_COUNTERS = frozenset({
    "good_counter", "stale_counter", "crashes",
})
LATENCY_HISTOGRAMS = frozenset({
    "good_hist", "stale_hist",
})
# Observatory field registries, seeded with the same two-way drift:
# each has a stale entry no producer emits, and each producer declares
# a rogue field missing here.
COST_CARD_FIELDS = frozenset({
    "schema", "stale_card_field",
})
LEDGER_ROW_FIELDS = frozenset({
    "source", "stale_row_field",
})
