"""Seeded purity violations (tests/test_static_analysis.py): a scan
body with a wall-clock call, a data-dependent Python branch, and a
Python coercion of a traced value. Never imported — AST fixture only."""
import time

import jax.numpy as jnp


def fake_round(cfg, st, r):
    t0 = time.time()                 # banned: host wall clock
    if st.timer > 0:                 # banned: branch on traced value
        bad = float(st.term)         # banned: coercion of traced value
        return bad
    f = lambda v: 1 if v > 0 else 2  # banned: branch on traced lambda param
    return jnp.where(st.timer > f(st.term), st.term, st.term)
