// Fixture native CLI: parses a flag the shared map does not know.
int parse(int argc, char** argv) {
  std::string k = argv[1];
  if (k == "--protocol") {}
  else if (k == "--nodes") {}
  else if (k == "--native-only") {}
  return 0;
}
