"""Fixture Config: `new_knob` is reachable from neither CLI."""


class Config:
    protocol: str = "raft"
    n_nodes: int = 5
    new_knob: int = 0
