"""Fixture flag map: 'ghost' maps to a field Config no longer has, and
NATIVE_CLI_TPU_ONLY carries a stale exemption."""

_FLAG_FIELDS = {
    "protocol": ("protocol", "raft"),
    "nodes": ("n_nodes", None),
    "ghost": ("gone_field", 1),
}

NATIVE_CLI_TPU_ONLY = frozenset({"stale_field"})
