// Fixture mirror: STREAM_B's value disagrees with the Python side and
// STREAM_C is absent entirely.
constexpr uint32_t STREAM_A = 0x11111111u;
constexpr uint32_t STREAM_B = 0x99999999u;
constexpr uint32_t STREAM_D = 0x33333333u;
