"""Seeded stream-registry violations (tests/test_static_analysis.py):
a constant collision, an unregistered constant, and a C++ mirror value
mismatch. Never imported — AST fixture only."""
import numpy as np

STREAM_A = np.uint32(0x11111111)
STREAM_B = np.uint32(0x11111111)   # collision with STREAM_A
STREAM_C = np.uint32(0x22222222)   # no STREAM_KEYS entry
STREAM_D = np.uint32(0x33333333)

STREAM_KEYS = {
    "STREAM_A": ("round", None, None),
    "STREAM_B": ("round", None, None),
    "STREAM_D": ("round", "src", "dst"),
}
STREAM_TPU_ONLY = frozenset()
STREAM_MIXER_ONLY = frozenset({"STREAM_D"})
