"""Seeded stream call-site violations: a non-literal in a pinned absorb
slot, an unregistered stream, and a threefry draw on a mixer-only
stream. Never imported — AST fixture only."""
from ..core import rng


def draw(seed, stream, ctx, c0, c1):
    return 0


def fake_round(seed, r, idx):
    a = draw(seed, rng.STREAM_A, r, idx, 0)   # pinned c0 slot varied
    b = draw(seed, rng.STREAM_X, r, 0, 0)     # unregistered stream
    c = draw(seed, rng.STREAM_D, r, 0, 0)     # mixer-only via threefry
    d = draw(seed, rng.STREAM_B, r, c0=idx, c1=0)   # pinned slot via keyword
    alias = rng.STREAM_B
    e = draw(seed, alias, r, idx, 0)          # pinned slot via aliased stream
    return a, b, c, d, e
