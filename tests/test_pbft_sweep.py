"""Batched PBFT f-sweep vs the unpadded engines and the C++ oracle.

The padding argument (engines/pbft_sweep.py): RNG draws are keyed by
absolute ids, never by N, so a padded sweep element must be *identical*
— not just equivalent — to the dedicated (N = 3f+1)-shaped program and
to the scalar oracle. Covered for BOTH fault models (the dense SPEC §6
round and the §6b bcast aggregate round — the former `--f-sweep`
carve-out, VERDICT weak #5) and for the independent-sweeps axis
(rung k sweep j == standalone run f=fs[k], seed=seed+k, sweep j).
"""
import dataclasses

import numpy as np
import pytest

from consensus_tpu.core.config import Config
from consensus_tpu.engines.pbft import pbft_run
from consensus_tpu.engines.pbft_sweep import pbft_fsweep_run
from consensus_tpu.oracle import bindings

BASE = Config(protocol="pbft", f=1, n_nodes=4, n_rounds=24, log_capacity=8,
              seed=7, drop_rate=0.15, partition_rate=0.05, churn_rate=0.05)
BCAST = dataclasses.replace(BASE, fault_model="bcast")
FS = [1, 2, 4]


def _rung_cfg(base, f, k, n_sweeps=1):
    return dataclasses.replace(base, f=f, n_nodes=3 * f + 1,
                               n_sweeps=n_sweeps, seed=base.seed + k)


def _assert_rung_equal(rung, exact):
    """Padded rung output ([K, n, S] arrays) vs a standalone batched
    run. dval is decided-log content only where committed (the
    serializer packs exactly those slots — core/serialize.py);
    elsewhere it is engine-internal scratch and may legitimately
    differ."""
    np.testing.assert_array_equal(rung["committed"], exact["committed"])
    c = rung["committed"].astype(bool)
    np.testing.assert_array_equal(rung["dval"][c].astype(np.uint32),
                                  exact["dval"][c].astype(np.uint32))
    np.testing.assert_array_equal(rung["view"], exact["view"])


@pytest.fixture(scope="module")
def sweep():
    return pbft_fsweep_run(BASE, FS)


@pytest.fixture(scope="module")
def bcast_sweep():
    return pbft_fsweep_run(BCAST, FS)


@pytest.mark.parametrize("k", range(len(FS)))
def test_padded_equals_unpadded_engine(sweep, k):
    exact = pbft_run(_rung_cfg(BASE, FS[k], k))
    _assert_rung_equal(sweep[k], exact)


@pytest.mark.parametrize("k", range(len(FS)))
def test_padded_equals_oracle(sweep, k):
    oracle = bindings.pbft_run(_rung_cfg(BASE, FS[k], k))
    c = oracle["committed"].astype(bool)
    np.testing.assert_array_equal(sweep[k]["committed"][0], c)
    np.testing.assert_array_equal(sweep[k]["dval"][0][c].astype(np.uint32),
                                  oracle["dval"][c].astype(np.uint32))


@pytest.mark.parametrize("k", range(len(FS)))
def test_bcast_padded_equals_unpadded_engine(bcast_sweep, k):
    """The §6b aggregate round with traced (n_real, f) must reproduce
    the dedicated engines/pbft_bcast.py program byte-for-byte."""
    exact = pbft_run(_rung_cfg(BCAST, FS[k], k))
    _assert_rung_equal(bcast_sweep[k], exact)


@pytest.mark.parametrize("k", range(len(FS)))
def test_bcast_padded_equals_oracle(bcast_sweep, k):
    oracle = bindings.pbft_run(_rung_cfg(BCAST, FS[k], k))
    c = oracle["committed"].astype(bool)
    np.testing.assert_array_equal(bcast_sweep[k]["committed"][0], c)
    np.testing.assert_array_equal(
        bcast_sweep[k]["dval"][0][c].astype(np.uint32),
        oracle["dval"][c].astype(np.uint32))


@pytest.mark.parametrize("base", [BASE, BCAST], ids=["edge", "bcast"])
def test_padded_sweeps_axis_equals_standalone(base):
    """The lifted --sweeps carve-out: K instances per rung as extra
    lanes — rung k must equal a standalone n_sweeps=K run (whose seed
    vector is lo32(seed + k + j), docs/SPEC.md §1), for both fault
    models."""
    multi = dataclasses.replace(base, n_sweeps=3)
    out = pbft_fsweep_run(multi, [1, 2])
    for k, f in enumerate([1, 2]):
        exact = pbft_run(_rung_cfg(base, f, k, n_sweeps=3))
        assert out[k]["committed"].shape[0] == 3
        _assert_rung_equal(out[k], exact)


def test_padded_equivocate_equals_unpadded():
    """The equivocating adversary must survive padding byte-identically
    (its draws are keyed by absolute ids, like every other stream) —
    under both fault granularities."""
    for fault_base in (BASE, BCAST):
        base = dataclasses.replace(fault_base, n_byzantine=1,
                                   byz_mode="equivocate", churn_rate=0.2)
        out = pbft_fsweep_run(base, [1, 2])
        for k, f in enumerate([1, 2]):
            exact = pbft_run(_rung_cfg(base, f, k))
            _assert_rung_equal(out[k], exact)


def test_padded_equivocate_f8_and_up(  # VERDICT r3 #5: ladder coverage
):
    """Padded-sweep equivocation at f >= 8: a full 8 equivocators inside
    sweep elements f=8 and f=16 (N_pad = 49) must match the unpadded
    engine and the scalar oracle byte-for-byte on committed slots."""
    base = dataclasses.replace(BASE, f=8, n_nodes=25, n_byzantine=8,
                               byz_mode="equivocate", churn_rate=0.1,
                               view_timeout=4, n_rounds=32)
    fs = [8, 16]
    out = pbft_fsweep_run(base, fs)
    for k, f in enumerate(fs):
        cfg = _rung_cfg(base, f, k)
        exact = pbft_run(cfg)
        _assert_rung_equal(out[k], exact)
        c = out[k]["committed"][0]
        oracle = bindings.pbft_run(cfg)
        np.testing.assert_array_equal(c, oracle["committed"].astype(bool))
        np.testing.assert_array_equal(out[k]["dval"][0][c].astype(np.uint32),
                                      oracle["dval"][c].astype(np.uint32))
        assert c.any(), f"f={f} equivocate sweep committed nothing"


def test_fsweep_validation():
    """Ladder guards fail fast: crash configs (§6c unmodeled), rungs
    below 1, and byz counts no rung can satisfy."""
    with pytest.raises(ValueError, match="crash-recover"):
        pbft_fsweep_run(dataclasses.replace(BASE, crash_prob=0.1,
                                            recover_prob=0.5), [1, 2])
    with pytest.raises(ValueError, match=">= 1"):
        pbft_fsweep_run(BASE, [0, 1])
    with pytest.raises(ValueError, match="n_byzantine"):
        pbft_fsweep_run(dataclasses.replace(BASE, f=2, n_nodes=7,
                                            n_byzantine=2), [1, 2])


def test_liveness_across_fs(sweep, bcast_sweep):
    # Every element of the sweep must actually commit something under this
    # mild adversary — otherwise the sweep benchmark measures idling.
    for tag, out in (("edge", sweep), ("bcast", bcast_sweep)):
        for k, o in enumerate(out):
            assert o["committed"].any(), \
                f"{tag} f={FS[k]} committed nothing"


def test_padded_desync_equals_unpadded():
    """SPEC §B timer skew must survive padding byte-identically (its
    draws are keyed by absolute ids) — under both fault granularities,
    composed with the delivery faults that keep views desynchronized."""
    for fault_base in (BASE, BCAST):
        base = dataclasses.replace(fault_base, desync_rate=0.2,
                                   max_skew_rounds=4, view_timeout=4)
        out = pbft_fsweep_run(base, [1, 2])
        for k, f in enumerate([1, 2]):
            exact = pbft_run(_rung_cfg(base, f, k))
            _assert_rung_equal(out[k], exact)
            # ... and the scalar oracle agrees with the padded rung.
            oracle = bindings.pbft_run(_rung_cfg(base, f, k))
            c = oracle["committed"].astype(bool)
            np.testing.assert_array_equal(out[k]["committed"][0], c)
            np.testing.assert_array_equal(
                out[k]["dval"][0][c].astype(np.uint32),
                oracle["dval"][c].astype(np.uint32))
