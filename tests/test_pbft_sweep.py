"""Batched PBFT f-sweep vs the unpadded engine and the C++ oracle.

The padding argument (engines/pbft_sweep.py): RNG draws are keyed by
absolute ids, never by N, so a padded sweep element must be *identical*
— not just equivalent — to the dedicated (N = 3f+1)-shaped program and
to the scalar oracle.
"""
import dataclasses

import numpy as np
import pytest

from consensus_tpu.core.config import Config
from consensus_tpu.engines.pbft import pbft_run
from consensus_tpu.engines.pbft_sweep import pbft_fsweep_run
from consensus_tpu.oracle import bindings

BASE = Config(protocol="pbft", f=1, n_nodes=4, n_rounds=24, log_capacity=8,
              seed=7, drop_rate=0.15, partition_rate=0.05, churn_rate=0.05)
FS = [1, 2, 4]


@pytest.fixture(scope="module")
def sweep():
    return pbft_fsweep_run(BASE, FS)


@pytest.mark.parametrize("k", range(len(FS)))
def test_padded_equals_unpadded_engine(sweep, k):
    f = FS[k]
    cfg = dataclasses.replace(BASE, f=f, n_nodes=3 * f + 1, n_sweeps=1,
                              seed=BASE.seed + k)
    exact = pbft_run(cfg)
    np.testing.assert_array_equal(sweep[k]["committed"], exact["committed"][0])
    # dval is decided-log content only where committed (the serializer
    # packs exactly those slots — core/serialize.py); elsewhere it is
    # engine-internal scratch and may legitimately differ.
    c = sweep[k]["committed"]
    np.testing.assert_array_equal(sweep[k]["dval"][c].astype(np.uint32),
                                  exact["dval"][0][c].astype(np.uint32))
    np.testing.assert_array_equal(sweep[k]["view"], exact["view"][0])


@pytest.mark.parametrize("k", range(len(FS)))
def test_padded_equals_oracle(sweep, k):
    f = FS[k]
    cfg = dataclasses.replace(BASE, f=f, n_nodes=3 * f + 1, n_sweeps=1,
                              seed=BASE.seed + k)
    oracle = bindings.pbft_run(cfg)
    c = oracle["committed"].astype(bool)
    np.testing.assert_array_equal(sweep[k]["committed"], c)
    np.testing.assert_array_equal(sweep[k]["dval"][c].astype(np.uint32),
                                  oracle["dval"][c].astype(np.uint32))


def test_padded_equivocate_equals_unpadded():
    """The equivocating adversary must survive padding byte-identically
    (its draws are keyed by absolute ids, like every other stream)."""
    base = dataclasses.replace(BASE, n_byzantine=1, byz_mode="equivocate",
                               churn_rate=0.2)
    out = pbft_fsweep_run(base, [1, 2])
    for k, f in enumerate([1, 2]):
        cfg = dataclasses.replace(base, f=f, n_nodes=3 * f + 1, n_sweeps=1,
                                  seed=base.seed + k)
        exact = pbft_run(cfg)
        np.testing.assert_array_equal(out[k]["committed"],
                                      exact["committed"][0])
        c = out[k]["committed"]
        np.testing.assert_array_equal(out[k]["dval"][c].astype(np.uint32),
                                      exact["dval"][0][c].astype(np.uint32))


def test_padded_equivocate_f8_and_up(  # VERDICT r3 #5: ladder coverage
):
    """Padded-sweep equivocation at f >= 8: a full 8 equivocators inside
    sweep elements f=8 and f=16 (N_pad = 49) must match the unpadded
    engine and the scalar oracle byte-for-byte on committed slots."""
    base = dataclasses.replace(BASE, f=8, n_nodes=25, n_byzantine=8,
                               byz_mode="equivocate", churn_rate=0.1,
                               view_timeout=4, n_rounds=32)
    fs = [8, 16]
    out = pbft_fsweep_run(base, fs)
    for k, f in enumerate(fs):
        cfg = dataclasses.replace(base, f=f, n_nodes=3 * f + 1, n_sweeps=1,
                                  seed=base.seed + k)
        exact = pbft_run(cfg)
        np.testing.assert_array_equal(out[k]["committed"],
                                      exact["committed"][0])
        c = out[k]["committed"]
        np.testing.assert_array_equal(out[k]["dval"][c].astype(np.uint32),
                                      exact["dval"][0][c].astype(np.uint32))
        oracle = bindings.pbft_run(cfg)
        np.testing.assert_array_equal(c, oracle["committed"].astype(bool))
        np.testing.assert_array_equal(out[k]["dval"][c].astype(np.uint32),
                                      oracle["dval"][c].astype(np.uint32))
        assert c.any(), f"f={f} equivocate sweep committed nothing"


def test_liveness_across_fs(sweep):
    # Every element of the sweep must actually commit something under this
    # mild adversary — otherwise the sweep benchmark measures idling.
    for k, out in enumerate(sweep):
        assert out["committed"].any(), f"f={FS[k]} committed nothing"
