"""DPoS: differential byte-equivalence + schedule invariants (SPEC §7)."""
import dataclasses

import numpy as np
import pytest

from consensus_tpu import Config
from consensus_tpu.network import simulator

from helpers import run_cached

BASE = Config(protocol="dpos", n_nodes=50, n_candidates=16, n_producers=4,
              epoch_len=16, n_rounds=96, log_capacity=128, n_sweeps=3,
              seed=888)
CFGS = [
    BASE,
    dataclasses.replace(BASE, drop_rate=0.3, churn_rate=0.1, seed=1),
    dataclasses.replace(BASE, n_nodes=200, n_candidates=32, n_producers=21,
                        drop_rate=0.2, partition_rate=0.1, seed=2),
    # Crosses the u8→u16 storage boundary on BOTH chain fields
    # (producer ids up to 299, round ids up to 299) — pins the
    # candidate-bounded chain_p dtype against the oracle.
    dataclasses.replace(BASE, n_nodes=300, n_candidates=300,
                        n_producers=21, n_rounds=300, drop_rate=0.1,
                        seed=3),
]


def test_dpos_config_rejects_candidates_exceeding_nodes():
    # Candidates are a subset of validators — the oracle rejects
    # C > V (cpp/oracle.cpp); Config must too, not run it one-sided.
    with pytest.raises(ValueError, match="n_candidates"):
        dataclasses.replace(BASE, n_nodes=100, n_candidates=600)
    with pytest.raises(ValueError, match="n_candidates"):
        dataclasses.replace(BASE, n_producers=40, n_candidates=16)


@pytest.mark.parametrize("cfg", CFGS)
def test_dpos_decided_log_byte_equivalence(cfg):
    tpu = run_cached(cfg)
    cpu = run_cached(dataclasses.replace(cfg, engine="cpu"))
    assert tpu.payload == cpu.payload, (tpu.digest, cpu.digest)


def test_dpos_blocks_come_from_scheduled_producers():
    """Every chain block's producer must be the scheduled one for its round
    — in EVERY sweep (each sweep derives its own schedule from seed+b)."""
    from consensus_tpu.engines.dpos import dpos_run, dpos_schedule
    from consensus_tpu.network.runner import make_seeds
    out = dpos_run(BASE)
    seeds = make_seeds(BASE)
    for b in range(BASE.n_sweeps):
        _, producers, _ = dpos_schedule(BASE, np.uint32(seeds[b]))
        producers = np.asarray(producers)
        for v in range(BASE.n_nodes):
            n = int(out["chain_len"][b, v])
            for k in range(n):
                r = int(out["chain_r"][b, v, k])
                e, t = r // BASE.epoch_len, r % BASE.epoch_len
                expect = producers[e, t % BASE.n_producers]
                assert out["chain_p"][b, v, k] == expect


@pytest.mark.parametrize("cfg", CFGS)
def test_dpos_lib_matches_oracle(cfg):
    """SPEC §7 last-irreversible block: the engine's vectorized closed
    form ((T-th largest last-occurrence) - 1) must equal the oracle's
    scalar nth_element derivation for every validator and sweep."""
    from consensus_tpu.engines.dpos import dpos_run
    from consensus_tpu.oracle import bindings
    out = dpos_run(cfg)
    for b in range(cfg.n_sweeps):
        oracle = bindings.dpos_run(cfg, sweep=b)
        np.testing.assert_array_equal(out["lib"][b], oracle["lib"])


def test_dpos_lib_exposed_by_simulator_both_engines():
    """SPEC §7 `lib` must be reachable through the simulator front door
    (RunResult.extras) from EITHER engine, not only via dpos_run/bindings
    (ADVICE r4), and agree with the dpos_run derivation."""
    from consensus_tpu.engines.dpos import dpos_run
    tpu = run_cached(BASE)
    cpu = run_cached(dataclasses.replace(BASE, engine="cpu"))
    ref = dpos_run(BASE)["lib"]
    np.testing.assert_array_equal(tpu.extras["lib"], ref)
    np.testing.assert_array_equal(cpu.extras["lib"], ref)


def test_dpos_lib_definition_brute_force():
    """lib[v] must be exactly the largest k whose suffix has >= T
    distinct producers (and lib+1 must violate it) — checked against a
    direct set-based reimplementation of the SPEC §7 definition."""
    from consensus_tpu.engines.dpos import dpos_run
    cfg = dataclasses.replace(BASE, drop_rate=0.3, churn_rate=0.15, seed=4)
    T = (2 * cfg.n_producers) // 3 + 1
    out = dpos_run(cfg)
    checked_some = False
    for b in range(cfg.n_sweeps):
        for v in range(cfg.n_nodes):
            n = int(out["chain_len"][b, v])
            chain = [int(p) for p in out["chain_p"][b, v, :n]]
            expect = -1
            for k in range(n):
                if len(set(chain[k + 1:])) >= T:
                    expect = k
            assert out["lib"][b, v] == expect, (b, v, chain)
            checked_some = checked_some or expect >= 0
    assert checked_some, "degenerate: no validator ever reached a LIB"


def test_dpos_tally_matches_numpy_oracle():
    """The stake-weighted segment-sum equals a straightforward numpy tally."""
    from consensus_tpu.core import rng
    from consensus_tpu.engines.dpos import dpos_schedule
    cfg = BASE
    stake, producers, tallies = dpos_schedule(cfg, np.uint32(cfg.seed))
    stake = np.asarray(stake)
    v_idx = np.arange(cfg.n_nodes, dtype=np.uint32)
    np_stake = rng.random_u32_np(cfg.seed, rng.STREAM_STAKE, 0, 0, v_idx) % 1000 + 1
    np.testing.assert_array_equal(stake, np_stake.astype(np.int32))
    for e in range(np.asarray(tallies).shape[0]):
        vote = rng.random_u32_np(cfg.seed, rng.STREAM_VOTE, e, 0, v_idx) % cfg.n_candidates
        expect = np.bincount(vote, weights=np_stake, minlength=cfg.n_candidates)
        np.testing.assert_array_equal(np.asarray(tallies)[e], expect.astype(np.int64))


def _lib_index_loop_reference(chain_p, chain_len, n_candidates, n_producers):
    """The pre-vectorization per-k host loop, kept verbatim as the
    reference the sorted/run-end form in engines.dpos.lib_index must
    reproduce bit-for-bit (it was the last per-element Python loop near
    a hot path; the rewrite is pure execution strategy)."""
    chain_p = np.asarray(chain_p)
    chain_len = np.asarray(chain_len)
    T = (2 * n_producers) // 3 + 1
    lead = chain_p.shape[:-1]
    L = chain_p.shape[-1]
    last_occ = np.full(lead + (n_candidates,), -1, np.int64)
    for k in range(L):
        mask = k < chain_len
        p = chain_p[..., k]
        if lead:
            idx = np.nonzero(mask)
            last_occ[idx + (p[idx],)] = k
        elif mask:
            last_occ[p] = k
    if T > n_candidates:
        return np.full(lead, -1, np.int64)
    lt = np.partition(last_occ, n_candidates - T, axis=-1)[..., n_candidates - T]
    return np.maximum(lt - 1, -1)


@pytest.mark.parametrize("lead,L,C,K,seed", [
    ((), 64, 16, 4, 0),          # scalar (no batch axes)
    ((7,), 128, 16, 4, 1),       # one batch axis
    ((3, 50), 96, 32, 21, 2),    # [sweep, validator], the dpos_run shape
    ((2, 9), 40, 8, 8, 3),       # T == C boundary (partition index 0)
    ((4,), 32, 4, 8, 4),         # T > C: everything -1
    ((5,), 1, 3, 2, 5),          # single-slot chains
    ((2, 3), 2048, 300, 21, 6),  # L in the thousands (the motivating size)
])
def test_lib_index_vectorized_bit_identical_to_loop(lead, L, C, K, seed):
    from consensus_tpu.engines.dpos import lib_index
    rs = np.random.RandomState(seed)
    chain_p = rs.randint(0, C, size=lead + (L,))
    # Mix empty, partial, and full chains (incl. len > L clamping never
    # happening by construction: chain_len <= L).
    chain_len = rs.randint(0, L + 1, size=lead)
    got = lib_index(chain_p, chain_len, C, K)
    want = _lib_index_loop_reference(chain_p, chain_len, C, K)
    np.testing.assert_array_equal(got, want)
    assert got.dtype == want.dtype and got.shape == want.shape
