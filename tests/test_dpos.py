"""DPoS: differential byte-equivalence + schedule invariants (SPEC §7)."""
import dataclasses

import numpy as np
import pytest

from consensus_tpu import Config
from consensus_tpu.network import simulator

from helpers import run_cached

BASE = Config(protocol="dpos", n_nodes=50, n_candidates=16, n_producers=4,
              epoch_len=16, n_rounds=96, log_capacity=128, n_sweeps=3,
              seed=888)
CFGS = [
    BASE,
    dataclasses.replace(BASE, drop_rate=0.3, churn_rate=0.1, seed=1),
    dataclasses.replace(BASE, n_nodes=200, n_candidates=32, n_producers=21,
                        drop_rate=0.2, partition_rate=0.1, seed=2),
    # Crosses the u8→u16 storage boundary on BOTH chain fields
    # (producer ids up to 299, round ids up to 299) — pins the
    # candidate-bounded chain_p dtype against the oracle.
    dataclasses.replace(BASE, n_nodes=300, n_candidates=300,
                        n_producers=21, n_rounds=300, drop_rate=0.1,
                        seed=3),
]


def test_dpos_config_rejects_candidates_exceeding_nodes():
    # Candidates are a subset of validators — the oracle rejects
    # C > V (cpp/oracle.cpp); Config must too, not run it one-sided.
    with pytest.raises(ValueError, match="n_candidates"):
        dataclasses.replace(BASE, n_nodes=100, n_candidates=600)
    with pytest.raises(ValueError, match="n_candidates"):
        dataclasses.replace(BASE, n_producers=40, n_candidates=16)


@pytest.mark.parametrize("cfg", CFGS)
def test_dpos_decided_log_byte_equivalence(cfg):
    tpu = run_cached(cfg)
    cpu = run_cached(dataclasses.replace(cfg, engine="cpu"))
    assert tpu.payload == cpu.payload, (tpu.digest, cpu.digest)


def test_dpos_blocks_come_from_scheduled_producers():
    """Every chain block's producer must be the scheduled one for its round
    — in EVERY sweep (each sweep derives its own schedule from seed+b)."""
    from consensus_tpu.engines.dpos import dpos_run, dpos_schedule
    from consensus_tpu.network.runner import make_seeds
    out = dpos_run(BASE)
    seeds = make_seeds(BASE)
    for b in range(BASE.n_sweeps):
        _, producers, _ = dpos_schedule(BASE, np.uint32(seeds[b]))
        producers = np.asarray(producers)
        for v in range(BASE.n_nodes):
            n = int(out["chain_len"][b, v])
            for k in range(n):
                r = int(out["chain_r"][b, v, k])
                e, t = r // BASE.epoch_len, r % BASE.epoch_len
                expect = producers[e, t % BASE.n_producers]
                assert out["chain_p"][b, v, k] == expect


@pytest.mark.parametrize("cfg", CFGS)
def test_dpos_lib_matches_oracle(cfg):
    """SPEC §7 last-irreversible block: the engine's vectorized closed
    form ((T-th largest last-occurrence) - 1) must equal the oracle's
    scalar nth_element derivation for every validator and sweep."""
    from consensus_tpu.engines.dpos import dpos_run
    from consensus_tpu.oracle import bindings
    out = dpos_run(cfg)
    for b in range(cfg.n_sweeps):
        oracle = bindings.dpos_run(cfg, sweep=b)
        np.testing.assert_array_equal(out["lib"][b], oracle["lib"])


def test_dpos_lib_exposed_by_simulator_both_engines():
    """SPEC §7 `lib` must be reachable through the simulator front door
    (RunResult.extras) from EITHER engine, not only via dpos_run/bindings
    (ADVICE r4), and agree with the dpos_run derivation."""
    from consensus_tpu.engines.dpos import dpos_run
    tpu = run_cached(BASE)
    cpu = run_cached(dataclasses.replace(BASE, engine="cpu"))
    ref = dpos_run(BASE)["lib"]
    np.testing.assert_array_equal(tpu.extras["lib"], ref)
    np.testing.assert_array_equal(cpu.extras["lib"], ref)


def test_dpos_lib_definition_brute_force():
    """lib[v] must be exactly the largest k whose suffix has >= T
    distinct producers (and lib+1 must violate it) — checked against a
    direct set-based reimplementation of the SPEC §7 definition."""
    from consensus_tpu.engines.dpos import dpos_run
    cfg = dataclasses.replace(BASE, drop_rate=0.3, churn_rate=0.15, seed=4)
    T = (2 * cfg.n_producers) // 3 + 1
    out = dpos_run(cfg)
    checked_some = False
    for b in range(cfg.n_sweeps):
        for v in range(cfg.n_nodes):
            n = int(out["chain_len"][b, v])
            chain = [int(p) for p in out["chain_p"][b, v, :n]]
            expect = -1
            for k in range(n):
                if len(set(chain[k + 1:])) >= T:
                    expect = k
            assert out["lib"][b, v] == expect, (b, v, chain)
            checked_some = checked_some or expect >= 0
    assert checked_some, "degenerate: no validator ever reached a LIB"


def test_dpos_tally_matches_numpy_oracle():
    """The stake-weighted segment-sum equals a straightforward numpy tally."""
    from consensus_tpu.core import rng
    from consensus_tpu.engines.dpos import dpos_schedule
    cfg = BASE
    stake, producers, tallies = dpos_schedule(cfg, np.uint32(cfg.seed))
    stake = np.asarray(stake)
    v_idx = np.arange(cfg.n_nodes, dtype=np.uint32)
    np_stake = rng.random_u32_np(cfg.seed, rng.STREAM_STAKE, 0, 0, v_idx) % 1000 + 1
    np.testing.assert_array_equal(stake, np_stake.astype(np.int32))
    for e in range(np.asarray(tallies).shape[0]):
        vote = rng.random_u32_np(cfg.seed, rng.STREAM_VOTE, e, 0, v_idx) % cfg.n_candidates
        expect = np.bincount(vote, weights=np_stake, minlength=cfg.n_candidates)
        np.testing.assert_array_equal(np.asarray(tallies)[e], expect.astype(np.int64))
