"""Sweepd — the persistent multi-tenant simulation service
(consensus_tpu/service, docs/SERVICE.md).

Layers under test:

  * the durable job queue (atomic journal, validation at admission,
    running->queued re-admission on restart);
  * the compatibility batcher (sweep-axis merge, knob lanes, solo
    fallback, the executable cache);
  * the end-to-end acceptance contract: two jobs sharing a (protocol,
    static shape) + one incompatible job — the compatible pair
    provably shares ONE compiled program (every dispatch span covers
    the pair; the jit cache does not grow for a repeat shape) and
    every job's digest is bit-identical to its standalone runner run;
  * durability: a daemon restarted over an in-flight job's state
    resumes from the job's own snapshot mid-scan (tier-1, doctored
    layout) — the real-SIGKILL daemon version lives in the slow tier;
  * the HTTP API, the per-job labeled gauges, the report artifact +
    ledger ingestion, and the CLI --submit client mode.
"""
from __future__ import annotations

import json
import pathlib
import signal
import subprocess
import sys
import time
import urllib.error
import urllib.request

import jax.numpy as jnp
import numpy as np
import pytest

from consensus_tpu import cli
from consensus_tpu.core.config import Config
from consensus_tpu.network import runner, simulator
from consensus_tpu.obs import metrics as obs_metrics
from consensus_tpu.obs import serve as obs_serve
from consensus_tpu.obs import trace as obs_trace
from consensus_tpu.service import (JOB_REPORT_FIELDS, JobQueue,
                                   SweepService, batcher, job_report_row)
from tools import validate_trace as vt

REPO = pathlib.Path(__file__).resolve().parents[1]

BASE = dict(protocol="raft", engine="tpu", n_nodes=5, n_rounds=64,
            n_sweeps=2, seed=3, log_capacity=32, max_entries=24)
OTHER = dict(BASE, protocol="paxos", n_nodes=9, n_rounds=48)


def _cfg(d: dict) -> Config:
    return Config.from_json(json.dumps(d))


def _post(url: str, doc: dict) -> dict:
    req = urllib.request.Request(url + "/jobs",
                                 data=json.dumps(doc).encode(),
                                 method="POST")
    return json.loads(urllib.request.urlopen(req, timeout=30).read())


def _get(url: str, path: str) -> dict:
    return json.loads(
        urllib.request.urlopen(url + path, timeout=30).read())


def _standalone_digest(config: dict) -> str:
    cfg = _cfg(config)
    kw = dict(stats={}, telemetry=True) if cfg.telemetry_window > 0 \
        else {}
    return simulator.run(cfg, warmup=False, **kw).digest


# --- metrics: labeled gauge families ----------------------------------------

def test_labeled_gauge_set_get_remove_snapshot():
    obs_metrics.reset()
    g = obs_metrics.labeled_gauge("svc_test_rounds")
    g.set(32, job="j0001")
    g.set(64, job="j0002")
    g.set(48, job="j0001")  # last write wins per child
    assert g.get(job="j0001") == 48
    assert g.get(job="missing") is None
    snap = obs_metrics.snapshot()["svc_test_rounds"]
    assert snap["type"] == "labeled_gauge"
    assert snap["series"] == [
        {"labels": {"job": "j0001"}, "value": 48},
        {"labels": {"job": "j0002"}, "value": 64}]
    g.remove(job="j0001")
    assert g.get(job="j0001") is None
    with pytest.raises(ValueError, match="at least one label"):
        g.set(1)


def test_labeled_gauge_prometheus_rendering_and_type_collision():
    obs_metrics.reset()
    obs_metrics.labeled_gauge("svc_test_eta").set(1.5, job='a"b')
    text = obs_metrics.to_prometheus()
    assert "# TYPE svc_test_eta gauge" in text
    assert 'svc_test_eta{job="a\\"b"} 1.5' in text
    with pytest.raises(TypeError, match="already registered"):
        obs_metrics.gauge("svc_test_eta")


def test_labeled_gauge_metrics_snapshot_validates(tmp_path):
    obs_metrics.reset()
    obs_metrics.labeled_gauge("svc_test_rounds").set(5, job="j1")
    path = tmp_path / "m.json"
    path.write_text(json.dumps({"version": 1,
                                "metrics": obs_metrics.snapshot()}))
    assert vt.validate_metrics(str(path)) == []
    bad = {"version": 1, "metrics": {"x": {
        "type": "labeled_gauge",
        "series": [{"labels": {}, "value": 1}]}}}
    path.write_text(json.dumps(bad))
    assert vt.validate_metrics(str(path))


# --- serve: routes + port-in-use + idempotent close -------------------------

def test_serve_routes_dispatch_get_post_and_404():
    calls = []

    def route(method, path, body):
        calls.append((method, path, body))
        return 200, "application/json", b'{"ok": true}\n'

    with obs_serve.MetricsServer(0, routes={"/jobs": route}) as srv:
        url = f"http://127.0.0.1:{srv.port}"
        assert _get(url, "/jobs") == {"ok": True}
        assert _get(url, "/jobs/j0001") == {"ok": True}  # prefix match
        req = urllib.request.Request(url + "/jobs", data=b'{"a":1}',
                                     method="POST")
        urllib.request.urlopen(req, timeout=10)
        # built-ins still win over the mounted prefix tree
        assert "uptime_s" in _get(url, "/status")
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(url, "/nope")
        assert exc.value.code == 404
    assert ("GET", "/jobs", b"") in calls
    assert ("POST", "/jobs", b'{"a":1}') in calls


def test_serve_port_in_use_is_a_clear_error_and_close_idempotent():
    srv = obs_serve.MetricsServer(0)
    with pytest.raises(obs_serve.PortInUseError,
                       match="already in use"):
        obs_serve.MetricsServer(srv.port)
    srv.close()
    srv.close()  # idempotent: a second close must not raise


# --- job queue ---------------------------------------------------------------

def test_queue_submit_validates_at_admission(tmp_path):
    q = JobQueue(tmp_path)
    with pytest.raises(ValueError):
        q.submit(dict(BASE, protocol="nope"))
    with pytest.raises(ValueError, match="seeds has"):
        q.submit(BASE, seeds=[1, 2, 3])
    with pytest.raises(ValueError):
        q.submit(BASE, scenario="no-such-scenario")
    with pytest.raises(ValueError, match="explicit seeds"):
        q.submit(dict(BASE, n_rounds=96, n_nodes=7),
                 seeds=[1, 2], scenario="delay-storm")
    with pytest.raises(ValueError, match="engine='tpu'"):
        q.submit(dict(BASE, engine="cpu", n_rounds=96, n_nodes=7),
                 scenario="delay-storm")
    assert q.jobs() == []  # nothing half-admitted


def test_queue_journal_roundtrip_and_readmission(tmp_path):
    q = JobQueue(tmp_path)
    j1 = q.submit(BASE, name="a")
    j2 = q.submit(OTHER, seeds=[7, 8])
    assert (j1.id, j2.id) == ("j0001", "j0002")
    j1.status = "running"
    q.update(j1)
    assert not q.path.with_suffix(".tmp.json").exists()  # atomic write

    q2 = JobQueue(tmp_path)  # the restart path
    r1, r2 = (q2.get("j0001"), q2.get("j0002"))
    assert r1.status == "queued" and r1.readmissions == 1
    assert q2.readmitted == ["j0001"]
    assert r2.status == "queued" and r2.seeds == [7, 8]
    assert r2.config["protocol"] == "paxos"
    # The re-admission was persisted, not just in-memory
    assert JobQueue(tmp_path).get("j0001").readmissions == 1


def test_job_order_is_numeric_past_the_zero_padding():
    from consensus_tpu.service.jobs import job_order
    ids = ["j10000", "j2000", "j0999", "j9999"]
    assert sorted(ids, key=job_order) == ["j0999", "j2000", "j9999",
                                          "j10000"]


def test_default_job_names_distinguish_shapes_not_seeds(tmp_path):
    """Default names key LEDGER series: different configs must never
    share one, same-shape different-seed jobs must (one honest
    series)."""
    q = JobQueue(tmp_path)
    a = q.submit(BASE)
    b = q.submit(dict(BASE, seed=99))            # same shape
    c = q.submit(dict(BASE, drop_rate=0.3))      # different workload
    assert a.name == b.name
    assert a.name != c.name
    assert a.name.startswith("raft-5n-64r-")


def test_job_report_fields_match_validator_registry(tmp_path):
    assert set(JOB_REPORT_FIELDS) == vt.SERVICE_JOB_FIELDS
    q = JobQueue(tmp_path)
    job = q.submit(BASE)
    job.status = "failed"
    job.error = "boom"
    job.finished_unix = time.time()
    q.update(job)
    q.write_reports(tmp_path / "r.json", "cpu")
    assert vt.validate_service_jobs(str(tmp_path / "r.json")) == []
    row = job_report_row(job, "cpu")
    assert set(row) == set(JOB_REPORT_FIELDS)


# --- batcher -----------------------------------------------------------------

def _job(q, config, **kw):
    return q.submit(config, **kw)


def test_plan_merges_sweep_compatible_pairs(tmp_path):
    q = JobQueue(tmp_path)
    a = _job(q, BASE)
    b = _job(q, dict(BASE, seed=77, n_sweeps=3))  # seed/sweeps differ only
    c = _job(q, OTHER)
    plan = batcher.plan([a, b, c])
    kinds = {p.kind: [j.id for j in p.jobs] for p in plan}
    assert kinds["merged"] == [a.id, b.id]
    assert kinds["solo"] == [c.id]


def test_plan_knob_lanes_require_matching_gates(tmp_path):
    q = JobQueue(tmp_path)
    kc = dict(BASE, telemetry_window=4, drop_rate=0.2)
    a = _job(q, kc)
    b = _job(q, dict(kc, drop_rate=0.4, seed=9))      # knob value only
    c = _job(q, dict(kc, crash_prob=0.1, recover_prob=0.3))  # gate flips
    d = _job(q, dict(BASE, drop_rate=0.2))            # recorder off
    plan = batcher.plan([a, b, c, d])
    knob_batches = [p for p in plan if p.kind == "knobs"]
    assert len(knob_batches) == 1
    assert [j.id for j in knob_batches[0].jobs] == [a.id, b.id]
    solo_ids = [p.jobs[0].id for p in plan if p.kind == "solo"]
    assert sorted(solo_ids) == [c.id, d.id]


def test_plan_solo_fallbacks(tmp_path):
    q = JobQueue(tmp_path)
    a = _job(q, dict(BASE, n_rounds=96, n_nodes=7, log_capacity=32),
             scenario="delay-storm")
    b = _job(q, dict(BASE, engine="cpu"))
    c = _job(q, dict(BASE, n_sweeps=4, sweep_chunk=2))
    for job in (a, b, c):
        assert batcher.sweep_key(job) is None
        assert batcher.knob_key(job) is None
    plan = batcher.plan([a, b, c])
    assert [p.kind for p in plan] == ["solo"] * 3


def test_executable_cache_key_ignores_seed_only(tmp_path):
    cache = batcher.ExecutableCache()
    k1 = cache.key("run", _cfg(BASE))
    k2 = cache.key("run", _cfg(dict(BASE, seed=99)))
    k3 = cache.key("run", _cfg(dict(BASE, n_sweeps=3)))
    assert k1 == k2 and k1 != k3
    assert cache.admit(k1) is False
    assert cache.admit(k2) is True
    assert (cache.hits, cache.misses) == (1, 1)


def test_effective_seeds_explicit_and_derived(tmp_path):
    q = JobQueue(tmp_path)
    a = _job(q, dict(BASE, seed=5, n_sweeps=3))
    np.testing.assert_array_equal(batcher.effective_seeds(a),
                                  np.asarray([5, 6, 7], np.uint32))
    b = _job(q, BASE, seeds=[11, 12])
    np.testing.assert_array_equal(batcher.effective_seeds(b),
                                  np.asarray([11, 12], np.uint32))


# --- end-to-end: the acceptance contract ------------------------------------

def test_service_batches_compatible_pair_and_digests_bit_identical(
        tmp_path, monkeypatch):
    """ISSUE acceptance: two jobs sharing a (protocol, shape) + one
    incompatible job submitted concurrently — the compatible pair
    provably shares one compiled program (every raft dispatch span
    covers the PAIR: exactly the chunk count of one merged run, not
    2x), and every job's digest is bit-identical to its standalone
    runner run."""
    obs_metrics.reset()
    q = JobQueue(tmp_path / "state")
    a = q.submit(BASE)
    b = q.submit(dict(BASE, seed=77, n_sweeps=3))
    c = q.submit(OTHER)
    trace = tmp_path / "t.jsonl"
    obs_trace.configure(str(trace))
    try:
        with SweepService(tmp_path / "state", port=0, platform="cpu",
                          batch_window_s=0, poll_s=0.01) as svc:
            url = f"http://127.0.0.1:{svc.port}"
            assert svc.wait_idle(180), _get(url, "/jobs")
            docs = {i: _get(url, f"/jobs/{i}")
                    for i in (a.id, b.id, c.id)}
    finally:
        obs_trace.close()

    assert docs[a.id]["batch"] == [a.id, b.id]
    assert docs[b.id]["batch"] == [a.id, b.id]
    assert docs[c.id]["batch"] is None
    for job, config in ((a, BASE),
                        (b, dict(BASE, seed=77, n_sweeps=3)),
                        (c, OTHER)):
        doc = docs[job.id]
        assert doc["status"] == "done", doc
        assert doc["result"]["digest"] == _standalone_digest(config)

    spans = [json.loads(line)
             for line in trace.read_text().splitlines()[1:]]
    disp = [s for s in spans if s.get("type") == "span"
            and s.get("name") == "dispatch"]
    # checkpoint-implied chunking: 64 rounds -> 2 chunks of 32 for the
    # merged raft PAIR, 48 -> 2 chunks of 24 for the solo paxos run.
    # 4 spans total — 6 would mean the pair ran separately.
    by_engine: dict = {}
    for s in disp:
        by_engine.setdefault(s["attrs"]["engine"], []).append(s)
    assert len(by_engine["raft"]) == 2, by_engine
    assert len(by_engine["paxos"]) == 2, by_engine
    kinds = [s["attrs"]["kind"] for s in spans
             if s.get("type") == "span" and s["name"] == "service_batch"]
    assert sorted(kinds) == ["merged", "solo"]


def test_service_knob_jobs_share_one_dispatch(tmp_path):
    """Tenants differing only in adversary knob values run as traced
    lanes of ONE run_knob_batch dispatch; digests stay bit-identical
    to their standalone runs (the PR 12 lane contract, now multi-
    tenant)."""
    obs_metrics.reset()
    kc = dict(BASE, telemetry_window=4, drop_rate=0.2, seed=5)
    kd = dict(kc, drop_rate=0.45, seed=9)
    q = JobQueue(tmp_path / "state")
    a, b = q.submit(kc), q.submit(kd)
    trace = tmp_path / "t.jsonl"
    obs_trace.configure(str(trace))
    try:
        with SweepService(tmp_path / "state", port=0, platform="cpu",
                          batch_window_s=0, poll_s=0.01) as svc:
            url = f"http://127.0.0.1:{svc.port}"
            assert svc.wait_idle(180), _get(url, "/jobs")
            docs = {i: _get(url, f"/jobs/{i}") for i in (a.id, b.id)}
    finally:
        obs_trace.close()
    assert docs[a.id]["batch"] == [a.id, b.id]
    for job, config in ((a, kc), (b, kd)):
        assert docs[job.id]["result"]["digest"] == \
            _standalone_digest(config)
    spans = [json.loads(line)
             for line in trace.read_text().splitlines()[1:]]
    disp = [s for s in spans if s.get("type") == "span"
            and s.get("name") == "dispatch"]
    assert len(disp) == 1, disp
    assert disp[0]["attrs"]["n_candidates"] == 4  # 2 jobs x 2 sweeps


def test_service_executable_cache_hit_no_recompile(tmp_path):
    """A repeat shape (same config, different seed) is an executable-
    cache hit: the /jobs doc says so, the counter moves, and — the
    hard witness — runner._chunk_jit's cache does NOT grow for the
    second job."""
    obs_metrics.reset()
    q = JobQueue(tmp_path / "state")
    first = q.submit(BASE)
    with SweepService(tmp_path / "state", port=0, platform="cpu",
                      batch_window_s=0, poll_s=0.01) as svc:
        url = f"http://127.0.0.1:{svc.port}"
        assert svc.wait_idle(180)
        assert _get(url, f"/jobs/{first.id}")["cache_hit"] is False
        size_before = runner._chunk_jit._cache_size()
        second = _post(url, {"config": dict(BASE, seed=1234)})
        deadline = time.time() + 120
        while _get(url, f"/jobs/{second['id']}")["status"] != "done":
            assert time.time() < deadline
            time.sleep(0.05)
        doc = _get(url, f"/jobs/{second['id']}")
        assert doc["cache_hit"] is True
        assert runner._chunk_jit._cache_size() == size_before
        assert doc["result"]["digest"] == \
            _standalone_digest(dict(BASE, seed=1234))
        snap = obs_metrics.snapshot()
        assert snap["service_exec_cache_hits_total"]["value"] >= 1


def test_service_scenario_job_carries_verdict(tmp_path):
    """A scenario job runs the scripted attack exactly like the CLI's
    --scenario: overrides applied at execution, the timeline verdict in
    the job doc and the report row (delay-storm at its tuned shape)."""
    obs_metrics.reset()
    shape = dict(protocol="raft", engine="tpu", n_nodes=7, n_rounds=96,
                 n_sweeps=2, seed=11, log_capacity=32, max_entries=24)
    q = JobQueue(tmp_path / "state")
    job = q.submit(shape, scenario="delay-storm")
    with SweepService(tmp_path / "state", port=0, platform="cpu",
                      batch_window_s=0, poll_s=0.01) as svc:
        url = f"http://127.0.0.1:{svc.port}"
        assert svc.wait_idle(240)
        doc = _get(url, f"/jobs/{job.id}")
    assert doc["status"] == "done", doc
    verdict = doc["result"]["scenario"]
    assert verdict["name"] == "delay-storm" and verdict["passed"], verdict
    # the verdict is durable: re-read through a fresh journal load
    row = job_report_row(JobQueue(tmp_path / "state").get(job.id), "cpu")
    assert row["scenario_passed"] is True


def test_service_durability_restart_resumes_mid_scan(tmp_path):
    """Tier-1 doctored-layout durability (the real-SIGKILL daemon
    version is the slow tier's): a job journaled as RUNNING with a
    genuine mid-run snapshot in its own directory is re-admitted on
    restart and RESUMED from round 32 — not recomputed — with the
    digest bit-identical to an uninterrupted standalone runner.run."""
    obs_metrics.reset()
    state = tmp_path / "state"
    q = JobQueue(state)
    job = q.submit(dict(BASE, n_rounds=64))
    # Doctor the in-flight state the way a killed daemon leaves it:
    # status=running in the journal, a valid snapshot at round 32 under
    # the job's own directory, written against the service's normalized
    # dispatch config (seed=0 + explicit seeds).
    cfg = job.cfg()
    seeds = batcher.effective_seeds(job)
    norm = batcher.normalized(cfg, cfg.n_sweeps)
    eng = simulator.engine_def(norm)
    carry = runner._init_jit(norm, eng, jnp.asarray(seeds))
    carry = runner._chunk_jit(norm, eng, 32, carry, jnp.int32(0))
    ckpt = q.job_dir(job.id) / "ck.npz"
    runner.save_checkpoint(ckpt, norm, carry, 32, seeds=seeds)
    job.status = "running"
    q.update(job)

    with SweepService(state, port=0, platform="cpu",
                      batch_window_s=0, poll_s=0.01) as svc:
        assert svc.queue.readmitted == [job.id]
        assert svc.wait_idle(180)
        doc = svc.queue.get(job.id)
    assert doc.status == "done", (doc.status, doc.error)
    assert doc.readmissions == 1
    assert doc.result["resumed_from_round"] == 32  # resumed, not rerun
    # Honest ledger accounting: steps count only the 32 rounds this
    # execution ran, not the checkpointed prefix (full-run steps over
    # a resumed wall clock would fake a throughput gain).
    assert doc.result["steps"] == 2 * 5 * 32
    assert doc.result["digest"] == _standalone_digest(dict(BASE,
                                                           n_rounds=64))


def test_service_grouped_job_uses_group_dir_layout(tmp_path):
    """A job asking for sweep_chunk grouping runs solo through the
    per-job --group-dir layout: per-group snapshot subdirectories +
    completed-group manifest under the job's own directory."""
    obs_metrics.reset()
    config = dict(BASE, n_sweeps=4, sweep_chunk=2, scan_chunk=16)
    state = tmp_path / "state"
    q = JobQueue(state)
    job = q.submit(config)
    with SweepService(state, port=0, platform="cpu",
                      batch_window_s=0, poll_s=0.01) as svc:
        assert svc.wait_idle(180)
        doc = svc.queue.get(job.id)
    assert doc.status == "done", (doc.status, doc.error)
    groups = q.job_dir(job.id) / "groups"
    assert (groups / "groups.json").exists()
    assert (groups / "group_0000" / "ck.npz").exists()
    assert doc.result["digest"] == _standalone_digest(config)


def test_service_http_api_validation_errors(tmp_path):
    obs_metrics.reset()
    with SweepService(tmp_path / "state", port=0, platform="cpu",
                      poll_s=0.01) as svc:
        url = f"http://127.0.0.1:{svc.port}"
        for body, needle in (
                (b"not json", "must be JSON"),
                (b"{}", "missing 'config'"),
                (json.dumps({"config": dict(BASE, protocol="nope")})
                 .encode(), "protocol")):
            req = urllib.request.Request(url + "/jobs", data=body,
                                         method="POST")
            with pytest.raises(urllib.error.HTTPError) as exc:
                urllib.request.urlopen(req, timeout=10)
            assert exc.value.code == 400
            assert needle in json.loads(exc.value.read())["error"]
        with pytest.raises(urllib.error.HTTPError) as exc:
            _get(url, "/jobs/j9999")
        assert exc.value.code == 404
        status = _get(url, "/status")
        assert status["service"] == "sweepd"
        assert status["jobs"] == {"queued": 0, "running": 0, "done": 0,
                                  "failed": 0}


def test_service_reports_validate_and_fold_into_ledger(tmp_path):
    """Completed-job rows validate against the field registry and fold
    into a ledger build as `service-job` rows with `new` single-point
    verdicts — never touching the regression list."""
    import tools.ledger as ledger
    obs_metrics.reset()
    state = tmp_path / "state"
    q = JobQueue(state)
    job = q.submit(BASE, name="svc-test-raft")
    with SweepService(state, port=0, platform="cpu", poll_s=0.01,
                      batch_window_s=0) as svc:
        assert svc.wait_idle(180)
    reports = state / "job_reports.json"
    assert vt.validate_service_jobs(str(reports)) == []

    repo = tmp_path / "repo"
    (repo / "benchmarks" / "parts").mkdir(parents=True)
    (repo / "benchmarks" / "parts" / "service_jobs.json").write_text(
        reports.read_text())
    doc = ledger.build(repo)
    rows = [r for r in doc["rows"] if r["kind"] == "service-job"]
    assert len(rows) == 1 and rows[0]["name"] == "svc-test-raft"
    assert rows[0]["ok"] is True and len(rows[0]["digest"]) == 64
    assert doc["series"]["svc-test-raft@cpu"]["verdict"] == "new"
    assert doc["regressions"] == []
    # the job is one digest-bearing measurement (fresh journal load —
    # the service persisted the result the moment the batch finished)
    done = JobQueue(state).get(job.id)
    assert rows[0]["digest"] == (done.result or {})["digest"]


def test_committed_service_jobs_artifact_schema_valid():
    path = REPO / "benchmarks" / "parts" / "service_jobs.json"
    assert path.exists(), "the committed sweepd report artifact is gone"
    assert vt.validate_service_jobs(str(path)) == []
    doc = json.loads(path.read_text())
    assert all(r["status"] == "done" for r in doc["rows"])


# --- CLI client mode ---------------------------------------------------------

def test_cli_submit_and_wait(tmp_path, capsys):
    obs_metrics.reset()
    with SweepService(tmp_path / "state", port=0, platform="cpu",
                      batch_window_s=0, poll_s=0.01) as svc:
        url = f"http://127.0.0.1:{svc.port}"
        rc = cli.main(["--protocol", "raft", "--nodes", "5",
                       "--rounds", "64", "--sweeps", "2", "--seed", "3",
                       "--log-capacity", "32", "--max-entries", "24",
                       "--submit", url, "--job-name", "cli-job"])
        assert rc == 0
        doc = json.loads(capsys.readouterr().out)
        assert doc["name"] == "cli-job" and doc["status"] == "queued"
        rc = cli.main(["--protocol", "raft", "--nodes", "5",
                       "--rounds", "64", "--sweeps", "2", "--seed", "88",
                       "--log-capacity", "32", "--max-entries", "24",
                       "--submit", url, "--submit-wait"])
        assert rc == 0
        final = json.loads(capsys.readouterr().out)
        assert final["status"] == "done"
        assert final["result"]["digest"] == _standalone_digest(
            dict(BASE, seed=88))


def test_cli_submit_rejects_local_execution_flags(tmp_path, capsys):
    with pytest.raises(SystemExit):
        cli.main(["--protocol", "raft", "--submit", "http://x",
                  "--checkpoint", str(tmp_path / "ck.npz")])
    assert "--checkpoint" in capsys.readouterr().err
    with pytest.raises(SystemExit):
        cli.main(["--protocol", "raft", "--submit-wait"])
    assert "--submit-wait requires --submit" in capsys.readouterr().err


def test_cli_submit_unreachable_service_is_a_clean_error(capsys):
    rc = cli.main(["--protocol", "raft",
                   "--submit", "http://127.0.0.1:9"])  # reserved port
    assert rc == 2
    assert "cannot reach" in capsys.readouterr().err


def test_cli_submit_rejection_round_trips_the_service_error(tmp_path,
                                                            capsys):
    obs_metrics.reset()
    with SweepService(tmp_path / "state", port=0, platform="cpu",
                      poll_s=0.01) as svc:
        url = f"http://127.0.0.1:{svc.port}"
        rc = cli.main(["--protocol", "pbft", "--f", "1",
                       "--scenario", "no-such-scenario",
                       "--submit", url])
    assert rc == 2
    assert "no-such-scenario" in capsys.readouterr().err


# --- hotstuff advsearch space (satellite) -----------------------------------

def test_hotstuff_advsearch_space_registered():
    """The view-timeout-storm search space: hotstuff protocol, short
    pacemaker timeout + bounded delay as static axes, mirrored (its
    knobs are all oracle-implemented, so findings CAN distill)."""
    from tools.advsearch.search import RATE_CUTOFFS, SPACES
    sp = SPACES["hotstuff-views"]
    assert sp.base.protocol == "hotstuff"
    assert sp.mirrored, "drop/partition/churn/delay are all mirrored"
    assert sp.base.view_timeout == 4      # the storm axis: short views
    assert sp.base.max_delay_rounds == 4  # §A.2 retransmissions on
    assert {k.field for k in sp.knobs} == {"drop_rate",
                                           "partition_rate",
                                           "churn_rate"}
    assert all(k.field in RATE_CUTOFFS for k in sp.knobs)
    # gate-representativity + range validity are construction-checked
    # (Space.__post_init__) and covered for every space by
    # tests/test_advsearch.py::test_space_definitions_are_gate_
    # representative.


# --- slow tier: the real daemon killed for real ------------------------------

@pytest.mark.slow
def test_daemon_sigkill_mid_job_restart_resumes_bit_identical(tmp_path):
    """ISSUE satellite: SIGKILL the daemon subprocess mid-job, restart
    it over the same state dir, and the finished job's digest is
    bit-identical to an uninterrupted standalone runner.run."""
    state = tmp_path / "state"
    config = dict(BASE, n_rounds=512, scan_chunk=16)

    def start():
        port_file = tmp_path / f"port-{time.time_ns()}"
        proc = subprocess.Popen(
            [sys.executable, "-m", "consensus_tpu.service", "--port",
             "0", "--state-dir", str(state), "--platform", "cpu",
             "--port-file", str(port_file), "--batch-window", "0"],
            cwd=REPO)
        deadline = time.time() + 120
        while not port_file.exists():
            assert proc.poll() is None, "daemon died at startup"
            assert time.time() < deadline, "daemon never bound"
            time.sleep(0.1)
        return proc, f"http://127.0.0.1:{port_file.read_text().strip()}"

    proc, url = start()
    try:
        jid = _post(url, {"config": config})["id"]
        # Wait until the job is demonstrably mid-flight (some rounds
        # done, not all), then SIGKILL — no graceful anything.
        deadline = time.time() + 180
        while True:
            doc = _get(url, f"/jobs/{jid}")
            done = doc.get("rounds_completed", 0)
            if doc["status"] == "running" and 0 < done < 512:
                break
            assert doc["status"] != "done", \
                "job finished before the kill — raise n_rounds"
            assert time.time() < deadline
            time.sleep(0.05)
        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=30)
        assert proc.returncode == -signal.SIGKILL
    finally:
        if proc.poll() is None:
            proc.kill()

    proc, url = start()
    try:
        deadline = time.time() + 300
        while True:
            doc = _get(url, f"/jobs/{jid}")
            if doc["status"] in ("done", "failed"):
                break
            assert time.time() < deadline
            time.sleep(0.2)
        assert doc["status"] == "done", doc.get("error")
        assert doc["readmissions"] >= 1
        assert doc["result"]["digest"] == _standalone_digest(config)
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            proc.kill()


def test_plan_is_deterministic_for_restart_reformation(tmp_path):
    """The merged-batch checkpoint story rests on this: the same
    re-admitted journal produces the same plan (same batches, same
    member order), so a restarted daemon finds its batch snapshots."""
    q = JobQueue(tmp_path)
    jobs = [q.submit(BASE), q.submit(dict(BASE, seed=77)),
            q.submit(OTHER), q.submit(dict(BASE, seed=5, n_sweeps=4))]
    p1 = batcher.plan(jobs)
    p2 = batcher.plan([JobQueue(tmp_path).get(j.id) for j in jobs])
    assert [(b.kind, tuple(j.id for j in b.jobs)) for b in p1] == \
        [(b.kind, tuple(j.id for j in b.jobs)) for b in p2]
    assert p1[0].kind == "merged" and len(p1[0].jobs) == 3
