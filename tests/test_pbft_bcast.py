"""SPEC §6b broadcast-atomic PBFT (engines/pbft_bcast.py): differential
byte-equivalence vs the oracle's independent scalar derivation
(cpp/oracle.cpp PbftSim with fault_bcast=1), coincidence with the dense
§6 engine when no faults exist, agreement safety under the coarse
equivocation adversary, and the large-N shapes the model exists for.
"""
import numpy as np
import pytest

from consensus_tpu import Config
from consensus_tpu.network import simulator


def _cfg(f=2, **kw):
    base = dict(protocol="pbft", fault_model="bcast", f=f, n_nodes=3 * f + 1,
                n_rounds=48, log_capacity=16, n_sweeps=2, seed=77,
                view_timeout=8, drop_rate=0.1, partition_rate=0.05,
                churn_rate=0.05)
    base.update(kw)
    return Config(**base)


CONFIGS = [
    ("f1", _cfg(f=1)),
    ("f2", _cfg(f=2)),
    ("f4-quiet", _cfg(f=4, drop_rate=0.0, partition_rate=0.0,
                      churn_rate=0.0)),
    ("f2-hostile", _cfg(f=2, drop_rate=0.3, partition_rate=0.2,
                        churn_rate=0.1, n_rounds=64, seed=5)),
    ("f2-byz-silent", _cfg(f=2, n_byzantine=2)),
    ("f2-byz-equiv", _cfg(f=2, n_byzantine=2, byz_mode="equivocate")),
    ("f8-byz-equiv", _cfg(f=8, n_byzantine=8, byz_mode="equivocate",
                          n_rounds=40, seed=11)),
    ("f10-mid", _cfg(f=10, n_rounds=32, seed=13)),
    # partition_rate=0 with drops/churn/equivocation live: exercises the
    # kernel's static no-partition specialization (one-sided tallies,
    # sorts, minima, byz extra) against the unspecialized oracle — the
    # BASELINE pbft-100k-bcast benchmark shape is exactly this class.
    ("f3-nopart-hostile", _cfg(f=3, drop_rate=0.2, partition_rate=0.0,
                               churn_rate=0.05, n_byzantine=3,
                               byz_mode="equivocate", n_rounds=64, seed=21)),
    # SPEC §B view desync under the broadcast-atomic fault model: the
    # per-(slot, side) aggregate round with genuinely skewed views.
    ("f2-desync", _cfg(f=2, desync_rate=0.2, max_skew_rounds=4,
                       view_timeout=4, seed=23)),
    # Mid-size §B shape (N = 301): wrap-around primaries + catch-up
    # healing at the population the bcast model exists for.
    ("f100-desync", _cfg(f=100, n_rounds=24, desync_rate=0.1,
                         max_skew_rounds=3, view_timeout=4, seed=29)),
]


@pytest.mark.parametrize("tag,cfg", CONFIGS, ids=[t for t, _ in CONFIGS])
def test_bcast_differential_vs_oracle(tag, cfg):
    tpu = simulator.run(cfg)
    cpu = simulator.run(Config(**{**cfg.__dict__, "engine": "cpu"}))
    assert tpu.payload == cpu.payload, (tag, tpu.digest, cpu.digest)


def test_bcast_equals_edge_model_when_faultless():
    """SPEC §6b: with no drops, partitions, or byzantine nodes, the two
    fault models describe the same (fault-free) execution."""
    kw = dict(drop_rate=0.0, partition_rate=0.0, churn_rate=0.02, seed=9)
    bcast = simulator.run(_cfg(f=2, **kw))
    edge = simulator.run(_cfg(f=2, fault_model="edge", **kw))
    assert bcast.payload == edge.payload, (bcast.digest, edge.digest)


def test_bcast_agreement_under_equivocation():
    """Committed values must agree across honest nodes per slot, with a
    full f of equivocating byzantine nodes (quorum-intersection +
    prepared-refusal, SPEC §6 safety argument — adversary-independent)."""
    cfg = _cfg(f=3, n_byzantine=3, byz_mode="equivocate", n_rounds=64,
               drop_rate=0.2, churn_rate=0.05, seed=21)
    out = simulator.run(cfg)
    n_honest = cfg.n_nodes - cfg.n_byzantine
    counts, rec_a, rec_b = out.counts, out.rec_a, out.rec_b  # [B,N], [B,N,L]
    committed_any = 0
    for b in range(cfg.n_sweeps):
        decided = {}
        for j in range(n_honest):
            for k in range(int(counts[b, j])):
                s, v = int(rec_a[b, j, k]), int(rec_b[b, j, k])
                assert decided.setdefault(s, v) == v, (b, j, s)
                committed_any += 1
    assert committed_any > 0, "degenerate: nothing committed"


def test_bcast_large_n_runs():
    """The shapes §6b exists for: N in the thousands, where the dense
    [N, N, S] engine would be ~10^9-element tensors. CPU-backend smoke +
    oracle differential at N=1501."""
    cfg = _cfg(f=500, n_nodes=1501, n_rounds=8, log_capacity=8, n_sweeps=1,
               drop_rate=0.05, seed=3)
    tpu = simulator.run(cfg)
    cpu = simulator.run(Config(**{**cfg.__dict__, "engine": "cpu"}))
    assert tpu.payload == cpu.payload
    assert out_commits(tpu) > 0


def out_commits(res):
    return int(np.asarray(res.counts).sum())


def test_fault_model_validation():
    with pytest.raises(ValueError):
        Config(protocol="raft", n_nodes=5, fault_model="bcast")
    with pytest.raises(ValueError):
        _cfg(fault_model="nonsense")


# --- sort-diet bit-identity vs the retired 3-sort round ----------------------
#
# The aggregate round (ONE payload sort, binary-search P1 order
# statistics, top-M run-table delivery) must reproduce the retired
# `_SortedTally` round — kept verbatim as a test-only reference
# (tests/reference_pbft_bcast.py) — on every state leaf AND telemetry
# counter, across the adversary grid and the populations the engine
# exists for. (N = 2047, not 2048: pbft requires n_nodes = 3f+1.)

DIET_CONFIGS = [
    ("N64-part-hostile", _cfg(f=21, n_nodes=64, n_rounds=24,
                              log_capacity=8, drop_rate=0.2,
                              partition_rate=0.2, churn_rate=0.05)),
    ("N64-byz-silent", _cfg(f=21, n_nodes=64, n_rounds=24, log_capacity=8,
                            n_byzantine=10, partition_rate=0.1)),
    ("N64-byz-equiv", _cfg(f=21, n_nodes=64, n_rounds=24, log_capacity=8,
                           n_byzantine=21, byz_mode="equivocate",
                           drop_rate=0.2, partition_rate=0.1, seed=31)),
    ("N64-crash", _cfg(f=21, n_nodes=64, n_rounds=24, log_capacity=8,
                       crash_prob=0.1, recover_prob=0.3, max_crashed=8,
                       partition_rate=0.1)),
    ("N1501", _cfg(f=500, n_nodes=1501, n_rounds=8, log_capacity=8,
                   n_sweeps=1, drop_rate=0.05, seed=3)),
    ("N2047-equiv-crash-part", _cfg(f=682, n_nodes=2047, n_rounds=6,
                                    log_capacity=8, n_sweeps=1,
                                    n_byzantine=100, byz_mode="equivocate",
                                    drop_rate=0.1, partition_rate=0.3,
                                    churn_rate=0.1, crash_prob=0.05,
                                    recover_prob=0.2, seed=13)),
]


@pytest.mark.parametrize("tag,cfg", DIET_CONFIGS,
                         ids=[t for t, _ in DIET_CONFIGS])
def test_diet_round_bit_identical_to_retired_round(tag, cfg):
    import sys
    import pathlib
    sys.path.insert(0, str(pathlib.Path(__file__).parent))
    from reference_pbft_bcast import reference_engine

    from consensus_tpu.engines import pbft_bcast
    from consensus_tpu.network import runner

    new_stats, ref_stats = {}, {}
    new = runner.run(cfg, pbft_bcast.get_engine(), stats=new_stats,
                     telemetry=True)
    ref = runner.run(cfg, reference_engine(), stats=ref_stats,
                     telemetry=True)
    for key in ref:
        np.testing.assert_array_equal(new[key], ref[key], err_msg=(tag, key))
    for name, vals in ref_stats["telemetry"].items():
        np.testing.assert_array_equal(new_stats["telemetry"][name], vals,
                                      err_msg=(tag, name))


def test_diet_round_scan_chunk_invariant():
    """The diet round under the production chunked scan: chunking must
    not change a single leaf (the runner contract every engine obeys —
    re-pinned here because the round was rewritten)."""
    import dataclasses

    from consensus_tpu.engines import pbft_bcast
    from consensus_tpu.network import runner

    cfg = _cfg(f=2, n_rounds=24)
    one = runner.run(cfg, pbft_bcast.get_engine())
    chunked = runner.run(dataclasses.replace(cfg, scan_chunk=7),
                         pbft_bcast.get_engine())
    for key in one:
        np.testing.assert_array_equal(one[key], chunked[key], err_msg=key)
