"""SPEC §6b broadcast-atomic PBFT (engines/pbft_bcast.py): differential
byte-equivalence vs the oracle's independent scalar derivation
(cpp/oracle.cpp PbftSim with fault_bcast=1), coincidence with the dense
§6 engine when no faults exist, agreement safety under the coarse
equivocation adversary, and the large-N shapes the model exists for.
"""
import numpy as np
import pytest

from consensus_tpu import Config
from consensus_tpu.network import simulator


def _cfg(f=2, **kw):
    base = dict(protocol="pbft", fault_model="bcast", f=f, n_nodes=3 * f + 1,
                n_rounds=48, log_capacity=16, n_sweeps=2, seed=77,
                view_timeout=8, drop_rate=0.1, partition_rate=0.05,
                churn_rate=0.05)
    base.update(kw)
    return Config(**base)


CONFIGS = [
    ("f1", _cfg(f=1)),
    ("f2", _cfg(f=2)),
    ("f4-quiet", _cfg(f=4, drop_rate=0.0, partition_rate=0.0,
                      churn_rate=0.0)),
    ("f2-hostile", _cfg(f=2, drop_rate=0.3, partition_rate=0.2,
                        churn_rate=0.1, n_rounds=64, seed=5)),
    ("f2-byz-silent", _cfg(f=2, n_byzantine=2)),
    ("f2-byz-equiv", _cfg(f=2, n_byzantine=2, byz_mode="equivocate")),
    ("f8-byz-equiv", _cfg(f=8, n_byzantine=8, byz_mode="equivocate",
                          n_rounds=40, seed=11)),
    ("f10-mid", _cfg(f=10, n_rounds=32, seed=13)),
    # partition_rate=0 with drops/churn/equivocation live: exercises the
    # kernel's static no-partition specialization (one-sided tallies,
    # sorts, minima, byz extra) against the unspecialized oracle — the
    # BASELINE pbft-100k-bcast benchmark shape is exactly this class.
    ("f3-nopart-hostile", _cfg(f=3, drop_rate=0.2, partition_rate=0.0,
                               churn_rate=0.05, n_byzantine=3,
                               byz_mode="equivocate", n_rounds=64, seed=21)),
]


@pytest.mark.parametrize("tag,cfg", CONFIGS, ids=[t for t, _ in CONFIGS])
def test_bcast_differential_vs_oracle(tag, cfg):
    tpu = simulator.run(cfg)
    cpu = simulator.run(Config(**{**cfg.__dict__, "engine": "cpu"}))
    assert tpu.payload == cpu.payload, (tag, tpu.digest, cpu.digest)


def test_bcast_equals_edge_model_when_faultless():
    """SPEC §6b: with no drops, partitions, or byzantine nodes, the two
    fault models describe the same (fault-free) execution."""
    kw = dict(drop_rate=0.0, partition_rate=0.0, churn_rate=0.02, seed=9)
    bcast = simulator.run(_cfg(f=2, **kw))
    edge = simulator.run(_cfg(f=2, fault_model="edge", **kw))
    assert bcast.payload == edge.payload, (bcast.digest, edge.digest)


def test_bcast_agreement_under_equivocation():
    """Committed values must agree across honest nodes per slot, with a
    full f of equivocating byzantine nodes (quorum-intersection +
    prepared-refusal, SPEC §6 safety argument — adversary-independent)."""
    cfg = _cfg(f=3, n_byzantine=3, byz_mode="equivocate", n_rounds=64,
               drop_rate=0.2, churn_rate=0.05, seed=21)
    out = simulator.run(cfg)
    n_honest = cfg.n_nodes - cfg.n_byzantine
    counts, rec_a, rec_b = out.counts, out.rec_a, out.rec_b  # [B,N], [B,N,L]
    committed_any = 0
    for b in range(cfg.n_sweeps):
        decided = {}
        for j in range(n_honest):
            for k in range(int(counts[b, j])):
                s, v = int(rec_a[b, j, k]), int(rec_b[b, j, k])
                assert decided.setdefault(s, v) == v, (b, j, s)
                committed_any += 1
    assert committed_any > 0, "degenerate: nothing committed"


def test_bcast_large_n_runs():
    """The shapes §6b exists for: N in the thousands, where the dense
    [N, N, S] engine would be ~10^9-element tensors. CPU-backend smoke +
    oracle differential at N=1501."""
    cfg = _cfg(f=500, n_nodes=1501, n_rounds=8, log_capacity=8, n_sweeps=1,
               drop_rate=0.05, seed=3)
    tpu = simulator.run(cfg)
    cpu = simulator.run(Config(**{**cfg.__dict__, "engine": "cpu"}))
    assert tpu.payload == cpu.payload
    assert out_commits(tpu) > 0


def out_commits(res):
    return int(np.asarray(res.counts).sum())


def test_fault_model_validation():
    with pytest.raises(ValueError):
        Config(protocol="raft", n_nodes=5, fault_model="bcast")
    with pytest.raises(ValueError):
        _cfg(fault_model="nonsense")
