"""consensus-lint (tools/lint): the repo is clean, and every check
catches its seeded-violation fixture (tests/fixtures/lint/<case>/ are
mini repo trees with one class of violation each).

The positive direction — `python -m tools.lint` exits 0 on the real
repo — is the tier-1 gate the ISSUE names: the determinism/parity
conventions (scan-body purity, stream registry, dtype discipline,
telemetry/crash-split registries, CLI flag surface) are enforced
statically from here on, not just probed dynamically.
"""
import pathlib
import subprocess
import sys

from tools.lint import CHECKS, run_checks

REPO = pathlib.Path(__file__).resolve().parents[1]
FIXTURES = REPO / "tests" / "fixtures" / "lint"


def _messages(case: str, check: str) -> str:
    root = FIXTURES / case
    assert root.is_dir(), f"fixture tree missing: {root}"
    return "\n".join(str(v) for v in run_checks(root, only=[check]))


def test_repo_is_clean():
    violations = run_checks(REPO)
    assert violations == [], "\n".join(str(v) for v in violations)


def test_module_entry_point_exits_zero():
    # The exact invocation `make check` / CI gate on.
    proc = subprocess.run([sys.executable, "-m", "tools.lint"],
                          cwd=REPO, capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr
    assert "consensus-lint: ok" in proc.stderr


def test_every_check_has_a_fixture_proving_it_fires():
    # A check that can never fire is decoration; each must catch its
    # seeded violation below. This meta-test pins the inventory.
    assert set(CHECKS) == {"purity", "streams", "dtypes", "registry",
                           "cli"}


def test_purity_catches_host_call_branch_and_coercion():
    msgs = _messages("purity_bad", "purity")
    assert "host call time.time()" in msgs
    assert "data-dependent Python branch" in msgs
    assert "float() coercion of a traced value" in msgs
    # Lambdas are the lax.cond/vmap-body idiom — their params are
    # traced too, so a ternary inside one must fire.
    assert "data-dependent Python ternary" in msgs


def test_dtypes_catches_64bit_and_defaulted_constructors():
    msgs = _messages("dtypes_bad", "dtypes")
    assert "jnp.int64" in msgs
    assert "jnp.zeros(...) without an explicit dtype" in msgs
    assert "jnp.arange(...) without an explicit dtype" in msgs
    assert "jnp.asarray(<literal>)" in msgs
    assert "FakeTable: jnp.ones(...)" in msgs            # class-level scope


def test_streams_catches_collision_registry_and_mirror_drift():
    msgs = _messages("streams_bad", "streams")
    assert "stream constant collision" in msgs           # A == B
    assert "STREAM_C has no STREAM_KEYS entry" in msgs
    assert "0x99999999" in msgs                          # cpp value mismatch
    assert "pins absorb slot c0" in msgs                 # non-literal pinned
    assert "unregistered stream STREAM_X" in msgs
    assert "mixer-only" in msgs                          # threefry on DELIVER
    # Keyword-arg and aliased-stream call sites cannot bypass the
    # pinned-slot rule (each must contribute its own c0 violation).
    assert msgs.count("pins absorb slot c0") >= 3


def test_registry_catches_telemetry_and_crash_split_drift():
    msgs = _messages("registry_bad", "registry")
    assert "'rogue_counter'" in msgs and "missing from" in msgs
    assert "'stale_counter'" in msgs and "reported by no engine" in msgs
    assert "recovery-reset fields ['timer']" in msgs     # declared persistent


def test_registry_catches_observatory_field_drift():
    # The cost-card / ledger exactly-these-keys registries drift both
    # ways like the telemetry counters: producer field missing from the
    # validator, validator entry emitted by no producer.
    msgs = _messages("registry_bad", "registry")
    assert "'rogue_card_field'" in msgs and "'stale_card_field'" in msgs
    assert "'rogue_row_field'" in msgs and "'stale_row_field'" in msgs
    assert "stale registry entry" in msgs


def test_cli_catches_unreachable_field_and_forked_flags():
    msgs = _messages("cli_bad", "cli")
    assert "Config.new_knob is unreachable from the Python CLI" in msgs
    assert "'gone_field'" in msgs and "not a Config field" in msgs
    assert "'stale_field'" in msgs
    assert "--native-only" in msgs and "forked" in msgs


def test_seeded_violation_in_real_tree_is_caught(tmp_path):
    # End-to-end on a COPY of the real engines tree: duplicate a stream
    # constant's value and the streams check must fire — proving the
    # check reads the real files, not just fixtures.
    import shutil
    root = tmp_path / "repo"
    for rel in ("consensus_tpu", "cpp", "tools"):
        shutil.copytree(REPO / rel, root / rel,
                        ignore=shutil.ignore_patterns("__pycache__",
                                                      "*.so", "*.o"))
    rng = root / "consensus_tpu" / "core" / "rng.py"
    text = rng.read_text().replace(
        "STREAM_CRASH = np.uint32(0x68E31DA5)",
        "STREAM_CRASH = np.uint32(0x9E3779B1)")  # collides with DELIVER
    assert text != rng.read_text()
    rng.write_text(text)
    msgs = "\n".join(str(v) for v in run_checks(root, only=["streams"]))
    assert "stream constant collision" in msgs
    assert "STREAM_CRASH" in msgs and "STREAM_DELIVER" in msgs
