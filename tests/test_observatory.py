"""Observatory layer 3: Prometheus rendering edge cases + the live
/metrics + /status endpoint (consensus_tpu/obs/serve.py).

The text a real scraper ingests must be exactly right — cumulative
le-buckets, escaped label values, last-write-wins gauges — and the
acceptance path is end-to-end: a subprocess CLI run under
``--serve-port`` must answer both endpoints MID-RUN on the CPU
backend.
"""
import json
import re
import subprocess
import sys
import urllib.error
import urllib.request

import pytest

from consensus_tpu.obs import metrics, serve


@pytest.fixture(autouse=True)
def _fresh_registry():
    metrics.reset()
    yield
    metrics.reset()


# --- Prometheus text rendering edge cases ------------------------------------

def test_histogram_buckets_render_cumulative_with_inf():
    h = metrics.histogram("t_s", buckets=(1.0, 2.0, 4.0))
    for v in (0.5, 0.5, 1.5, 3.0, 100.0):   # 100.0 -> overflow bucket
        h.observe(v)
    text = metrics.to_prometheus()
    lines = [ln for ln in text.splitlines() if ln.startswith("t_s_bucket")]
    # Non-cumulative counts are (2, 1, 1, 1); the rendering must be
    # the running sum, with +Inf == count (the overflow bucket lives
    # ONLY inside +Inf — a scraper summing le-buckets must not lose it).
    assert lines == ['t_s_bucket{le="1.0"} 2', 't_s_bucket{le="2.0"} 3',
                     't_s_bucket{le="4.0"} 4', 't_s_bucket{le="+Inf"} 5']
    assert "t_s_count 5" in text
    assert h.count == sum(h.counts)  # snapshot stays non-cumulative


def test_gauge_overwrite_renders_last_write_only():
    g = metrics.gauge("rounds_completed")
    g.set(16)
    g.set(64)
    text = metrics.to_prometheus()
    assert "rounds_completed 64" in text
    assert "rounds_completed 16" not in text


def test_label_value_escaping():
    assert metrics.escape_label_value('a"b') == 'a\\"b'
    assert metrics.escape_label_value("a\\b") == "a\\\\b"
    assert metrics.escape_label_value("a\nb") == "a\\nb"
    metrics.info("run_info").set(platform='tpu "v5e"\ntunnel',
                                 protocol="raft")
    text = metrics.to_prometheus()
    [line] = [ln for ln in text.splitlines()
              if ln.startswith("run_info{")]
    assert line == ('run_info{platform="tpu \\"v5e\\"\\ntunnel",'
                    'protocol="raft"} 1')
    assert "\n tunnel" not in text  # no raw newline inside a label


def test_info_metric_snapshot_and_type_collision():
    metrics.info("run_info").set(engine="tpu")
    snap = metrics.snapshot()
    assert snap["run_info"] == {"type": "info", "labels": {"engine": "tpu"}}
    with pytest.raises(TypeError):
        metrics.counter("run_info")


def test_info_metric_validates(tmp_path):
    import pathlib
    import sys as _sys
    _sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))
    from tools import validate_trace
    metrics.info("run_info").set(protocol="raft")
    metrics.counter("x_total").inc()
    p = tmp_path / "m.json"
    p.write_text(json.dumps({"version": 1, "metrics": metrics.snapshot()}))
    assert not validate_trace.validate_metrics(p)
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"version": 1, "metrics": {
        "run_info": {"type": "info", "labels": {"k": 3}}}}))
    assert validate_trace.validate_metrics(bad)


# --- the server, in-process --------------------------------------------------

def _get(port: int, path: str):
    return urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10)


def test_metrics_server_serves_registry_and_status():
    metrics.counter("checkpoint_saves_total").inc(2)
    metrics.gauge("rounds_completed").set(32)
    metrics.gauge("sim_eta_s").set(1.5)
    with serve.MetricsServer(0, status=lambda: {"protocol": "raft",
                                                "n_rounds": 64}) as srv:
        body = _get(srv.port, "/metrics").read().decode()
        assert "# TYPE checkpoint_saves_total counter" in body
        assert "checkpoint_saves_total 2" in body
        st = json.load(_get(srv.port, "/status"))
        assert st["protocol"] == "raft" and st["n_rounds"] == 64
        assert st["rounds_completed"] == 32 and st["sim_eta_s"] == 1.5
        assert st["uptime_s"] >= 0
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(srv.port, "/nope")
        assert ei.value.code == 404
    # Closed: the port no longer answers.
    with pytest.raises(urllib.error.URLError):
        _get(srv.port, "/metrics")


def test_metrics_server_status_without_callable():
    with serve.MetricsServer(0) as srv:
        st = json.load(_get(srv.port, "/status"))
        assert "rounds_completed" in st and "sim_eta_s" in st


def test_scraper_disconnect_is_silent(capfd):
    import socket
    metrics.histogram("h_s").observe(0.01)
    with serve.MetricsServer(0) as srv:
        # A scraper that sends the request and slams the socket shut:
        # the handler's write hits a dead pipe. The run's stderr must
        # stay clean — no socketserver traceback spam.
        s = socket.create_connection(("127.0.0.1", srv.port), timeout=5)
        s.sendall(b"GET /metrics HTTP/1.1\r\nHost: x\r\n\r\n")
        s.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                     b"\x01\x00\x00\x00\x00\x00\x00\x00")  # RST on close
        s.close()
        # A follow-up well-behaved scrape proves the server survived.
        assert "h_s_count" in _get(srv.port, "/metrics").read().decode()
    out, err = capfd.readouterr()
    assert "Traceback" not in err and "Exception occurred" not in err


# --- acceptance: subprocess CLI run, scraped mid-run -------------------------

def test_cli_serve_port_answers_mid_run(tmp_path):
    """A real `--serve-port 0` run on the CPU backend: read the bound
    port off the stderr banner, scrape /metrics and /status while the
    subprocess is still executing (the server starts before
    compile, so the window covers warmup + every chunk), then let the
    run finish and check its report — the Observatory acceptance
    path."""
    proc = subprocess.Popen(
        [sys.executable, "-m", "consensus_tpu", "--protocol", "raft",
         "--nodes", "32", "--rounds", "256", "--scan-chunk", "16",
         "--sweeps", "2", "--log-capacity", "32", "--max-entries", "16",
         "--engine", "tpu", "--platform", "cpu", "--serve-port", "0"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True)
    try:
        port = None
        for line in proc.stderr:
            m = re.search(r"serve: listening on http://127\.0\.0\.1:(\d+)",
                          line)
            if m:
                port = int(m.group(1))
                break
        assert port, "no serve banner on stderr"
        assert proc.poll() is None, "run finished before the scrape"

        body = _get(port, "/metrics").read().decode()
        assert 'run_info{' in body and 'protocol="raft"' in body
        st = json.load(_get(port, "/status"))
        assert st["protocol"] == "raft" and st["engine"] == "tpu"
        assert st["n_rounds"] == 256 and st["pid"] == proc.pid
        assert isinstance(st["rounds_completed"], (int, float))
        assert st["rounds_completed"] <= 256
    finally:
        out, err = proc.communicate(timeout=240)
    assert proc.returncode == 0, err
    report = json.loads(out)
    assert report["protocol"] == "raft" and len(report["digest"]) == 64
