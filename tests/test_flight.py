"""Flight recorder (docs/OBSERVABILITY.md §"Flight recorder").

Contracts under test:

  1. **Digest neutrality + series soundness** — every engine's run with
     ``telemetry_window > 0`` is bit-identical to the recorder-off run,
     and the window ring sums (over the window axis) to exactly the
     per-sweep telemetry totals: the series IS the counters, windowed.
  2. **Invariance** — the series is unchanged under ``scan_chunk`` /
     ``sweep_chunk`` re-chunking, and the recorder-ON program compiled
     for a sweep-only mesh stays collective-free (trace time).
  3. **Checkpoint/resume of the ring** — the window ring + latency
     histograms ride the snapshot: a resumed run's series covers the
     WHOLE trajectory, bit-identically (SIGKILL variant in the slow
     tier); a recorder on/off mismatched snapshot is skipped LOUDLY
     (schema-skip), never a shape crash — both directions.
  4. **Timeline analysis** — ``obs/timeline.py`` derives availability /
     stall / recovery metrics a scripted election-disruption run must
     exhibit (the ROADMAP adversary-assertion primitive), pinned
     exactly on synthetic series.
  5. **Artifacts** — a fresh ``--telemetry-window`` CLI run's metrics
     JSON + report validate under tools/validate_trace.py (subprocess,
     as CI runs it), drift is rejected, and ``tools/teleview`` renders
     both the metrics and the checkpoint form.
"""
import dataclasses
import importlib.util
import json
import os
import pathlib
import signal
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensus_tpu.core.config import Config
from consensus_tpu.network import faults, runner, simulator, supervisor
from consensus_tpu.obs import timeline
from consensus_tpu.ops import flight as flightlib

from helpers import run_cached

REPO = pathlib.Path(__file__).resolve().parents[1]
ADV = dict(drop_rate=0.1, partition_rate=0.05, churn_rate=0.05)

# telemetry_window chosen per config so several rings end in a RAGGED
# last window (n_rounds not divisible by W) — the geometry that breaks
# first if the window index math drifts.
CFGS = {
    "raft": Config(protocol="raft", n_nodes=5, n_rounds=48, n_sweeps=2,
                   log_capacity=32, max_entries=16, telemetry_window=10,
                   **ADV),
    "pbft": Config(protocol="pbft", f=1, n_nodes=4, n_rounds=24,
                   log_capacity=8, telemetry_window=8, **ADV),
    "paxos": Config(protocol="paxos", n_nodes=7, n_rounds=24,
                    log_capacity=8, telemetry_window=7, **ADV),
    "dpos": Config(protocol="dpos", n_nodes=24, n_rounds=32,
                   log_capacity=48, n_candidates=8, n_producers=3,
                   epoch_len=8, telemetry_window=16, **ADV),
    "raft-sparse": Config(protocol="raft", n_nodes=64, max_active=4,
                          n_rounds=32, n_sweeps=2, log_capacity=16,
                          max_entries=8, telemetry_window=5, **ADV),
    "pbft-bcast": Config(protocol="pbft", fault_model="bcast", f=5,
                         n_nodes=16, n_rounds=24, log_capacity=8,
                         telemetry_window=6, **ADV),
    "hotstuff": Config(protocol="hotstuff", f=1, n_nodes=4, n_rounds=24,
                       log_capacity=24, telemetry_window=6, **ADV),
}


def _run_flight(cfg, **kw):
    return simulator.run(cfg, warmup=False, telemetry=True, **kw)


def _off(cfg):
    return dataclasses.replace(cfg, telemetry_window=0)


# --- 1. digest neutrality + series soundness --------------------------------

@pytest.mark.parametrize("name", list(CFGS))
def test_recorder_digest_neutral_and_windows_sum_to_totals(name):
    cfg = CFGS[name]
    on = _run_flight(cfg)
    assert on.payload == run_cached(_off(cfg)).payload
    fl = on.extras["flight"]
    per = on.extras["telemetry"]["per_sweep"]
    assert fl["n_windows"] == -(-cfg.n_rounds // cfg.telemetry_window)
    assert set(fl["windows"]) == set(per)
    for k, series in fl["windows"].items():
        assert series.shape == (cfg.n_sweeps, fl["n_windows"])
        assert (series >= 0).all(), k
        # The ring is the counters, WINDOWED: collapse the time axis
        # and the totals must match exactly.
        np.testing.assert_array_equal(series.sum(axis=1), per[k],
                                      err_msg=k)
    eng = simulator.engine_def(cfg)
    assert set(fl["latency"]) == set(eng.latency_names)
    for k, h in fl["latency"].items():
        assert h.shape == (cfg.n_sweeps, flightlib.N_BUCKETS)
        assert (h >= 0).all(), k
    assert fl["bucket_lo"] == list(flightlib.BUCKET_LO)


def test_dpos_latency_observations_one_per_round():
    # chain_lag_rounds records exactly one observation per round — the
    # bucket totals are a full census of the run.
    cfg = CFGS["dpos"]
    fl = _run_flight(cfg).extras["flight"]
    np.testing.assert_array_equal(
        fl["latency"]["chain_lag_rounds"].sum(axis=1),
        np.full(cfg.n_sweeps, cfg.n_rounds))


# --- 2. invariance -----------------------------------------------------------

@pytest.mark.parametrize("repl", [dict(scan_chunk=7), dict(scan_chunk=1),
                                  dict(sweep_chunk=1)],
                         ids=["scan_chunk7", "scan_chunk1", "sweep_chunk"])
def test_series_invariant_to_chunking(repl):
    base = _run_flight(CFGS["raft"])
    got = _run_flight(dataclasses.replace(CFGS["raft"], **repl))
    assert got.payload == base.payload
    for k, v in base.extras["flight"]["windows"].items():
        np.testing.assert_array_equal(got.extras["flight"]["windows"][k],
                                      v, err_msg=k)
    for k, v in base.extras["flight"]["latency"].items():
        np.testing.assert_array_equal(got.extras["flight"]["latency"][k],
                                      v, err_msg=k)


def test_recorder_program_sweep_mesh_collective_free():
    """Trace-time: the recorder-ON chunk program compiled for a
    sweep-only mesh emits ZERO collectives (sweeps stay independent
    simulators — the ring is sweep-sharded like the accumulator)."""
    from tools.hlocheck import hlo
    cfg = dataclasses.replace(CFGS["raft"], n_sweeps=8)
    rep = hlo.compiled_report(cfg, mesh_shape=(8,), flight=True)
    assert not rep.collectives
    assert not rep.wide_dtypes and not rep.host_ops


def test_recorder_program_flagship_sort_budget_holds():
    """Trace-time at the TRUE pbft-100k-bcast shape: the recorder-ON
    program keeps the PR 8 sort diet (sort <= 1) — windows must not
    reintroduce sort/cumsum-class ops (also pinned continuously by the
    pbft-100k-bcast-flight hlocheck fingerprint)."""
    from tools.hlocheck import contracts, hlo, registry
    tgt = registry.target("pbft-100k-bcast-flight")
    rep = hlo.compiled_report(tgt.cfg, flight=True)
    con = contracts.program_contracts()["pbft-bcast"]
    assert rep.sort_ops <= con.sort_budget == 1
    assert rep.cumsum_ops <= con.cumsum_budget


# --- 3. checkpoint/resume of the ring ---------------------------------------

def test_ring_rides_checkpoint_and_resume_covers_whole_run(tmp_path):
    ck = tmp_path / "ck.npz"
    cfg = dataclasses.replace(CFGS["raft"], scan_chunk=16)
    full = _run_flight(cfg, checkpoint_path=str(ck), resume=True)
    base = _run_flight(CFGS["raft"])
    assert full.payload == base.payload
    for k, v in base.extras["flight"]["windows"].items():
        np.testing.assert_array_equal(
            full.extras["flight"]["windows"][k], v, err_msg=k)
    # Resume from the last mid-run snapshot (round 32 of 48): the ring
    # rode the snapshot, so the resumed series still covers ALL windows
    # — while the (deliberately un-checkpointed) telemetry totals cover
    # only the executed tail.
    stats: dict = {}
    res = _run_flight(cfg, checkpoint_path=str(ck), resume=True,
                      stats=stats)
    assert stats["start_round"] == 32
    assert res.payload == base.payload
    for k, v in base.extras["flight"]["windows"].items():
        np.testing.assert_array_equal(
            res.extras["flight"]["windows"][k], v, err_msg=k)
    for k, v in base.extras["flight"]["latency"].items():
        np.testing.assert_array_equal(
            res.extras["flight"]["latency"][k], v, err_msg=k)
    tot = res.extras["telemetry"]["totals"]["entries_committed"]
    assert tot <= base.extras["telemetry"]["totals"]["entries_committed"]


def test_checkpoint_schema_skip_both_directions(tmp_path, capsys):
    """A snapshot written with the recorder OFF must not shape-crash a
    recorder-ON run (and vice versa): the leaf-count mismatch is a loud
    schema skip — the run restarts from round 0 with a stderr message,
    exactly like any carry schema from another era."""
    cfg = _off(CFGS["raft"])
    fcfg = CFGS["raft"]
    eng = simulator.engine_def(cfg)
    seeds = jnp.asarray(runner.make_seeds(cfg))
    carry = runner._init_jit(cfg, eng, seeds)
    carry = runner._chunk_jit(cfg, eng, 16, carry, jnp.int32(0))
    rt = (jax.ShapeDtypeStruct(
              (cfg.n_sweeps, runner.n_windows(fcfg),
               len(eng.telemetry_names)), jnp.int32),
          jax.ShapeDtypeStruct(
              (cfg.n_sweeps, len(eng.latency_names),
               flightlib.N_BUCKETS), jnp.int32))

    # OFF-written snapshot, ON loader -> loud skip, not a crash.
    off_ck = tmp_path / "off.npz"
    runner.save_checkpoint(off_ck, cfg, carry, 16)
    assert runner.load_checkpoint(off_ck, fcfg, eng,
                                  recorder_template=rt) is None
    err = capsys.readouterr().err
    assert "leaves" in err and "skipping" in err

    # ON-written snapshot, OFF loader -> same loud degradation.
    on_ck = tmp_path / "on.npz"
    win = jnp.zeros(rt[0].shape, jnp.int32)
    lat = jnp.zeros(rt[1].shape, jnp.int32)
    runner.save_checkpoint(on_ck, fcfg, (carry, win, lat), 16)
    assert runner.load_checkpoint(on_ck, cfg, eng) is None
    err = capsys.readouterr().err
    assert "leaves" in err and "skipping" in err

    # ... and the matching directions both load.
    got = runner.load_checkpoint(off_ck, cfg, eng)
    assert got is not None and got[1] == 16
    got = runner.load_checkpoint(on_ck, fcfg, eng, recorder_template=rt)
    assert got is not None and got[1] == 16
    (got_carry, got_win, got_lat), _ = got
    assert np.asarray(got_win).shape == rt[0].shape

    # ON-written under W=10, loaded under W=5: SAME leaf count but a
    # different ring geometry — must be the loud shape skip, never a
    # silently mis-shaped series (the shape check is the backstop
    # behind the meta rejection below).
    w5 = dataclasses.replace(fcfg, telemetry_window=5)
    assert runner.n_windows(w5) != runner.n_windows(fcfg)
    assert runner.load_checkpoint(on_ck, w5, eng,
                                  recorder_template=runner.flight_structs(
                                      w5, eng)) is None

    # ... and two recorder-ON runs whose differing W happens to yield
    # the SAME n_windows (48 rounds: ceil/10 == ceil/11 == 5) must
    # also not resume — the saved ring's bins mean rounds [i*10, ...),
    # not [i*11, ...). _meta_matches compares nonzero W directly.
    w11 = dataclasses.replace(fcfg, telemetry_window=11)
    assert runner.n_windows(w11) == runner.n_windows(fcfg)
    assert runner.load_checkpoint(on_ck, w11, eng,
                                  recorder_template=runner.flight_structs(
                                      w11, eng)) is None
    capsys.readouterr()


def test_from_checkpoint_truncates_to_executed_rounds(tmp_path):
    """A MID-RUN recorder snapshot covers rounds [0, next_round) only:
    timeline.from_checkpoint must truncate to the executed windows —
    never-executed windows must not read as stalls and deflate the
    derived availability."""
    cfg = CFGS["raft"]                       # 48 rounds, W=10
    eng = simulator.engine_def(cfg)
    seeds = jnp.asarray(runner.make_seeds(cfg))
    telem = jnp.zeros((cfg.n_sweeps, len(eng.telemetry_names)), jnp.int32)
    rt = runner.flight_structs(cfg, eng)
    win = jnp.zeros(rt[0].shape, jnp.int32)
    lat = jnp.zeros(rt[1].shape, jnp.int32)
    carry = runner._init_jit(cfg, eng, seeds)
    carry, telem, win, lat = runner._chunk_jit(cfg, eng, 16, carry,
                                               jnp.int32(0), telem, win, lat)
    ck = tmp_path / "mid.npz"
    runner.save_checkpoint(ck, cfg, (carry, win, lat), 16)
    tl = timeline.from_checkpoint(ck)
    # 16 executed rounds -> ceil(16/10) = 2 windows, ragged last (6 r).
    assert (tl.n_rounds, tl.n_windows) == (16, 2)
    assert list(tl.rounds_in_window()) == [10, 6]
    assert tl.windows["entries_committed"].shape == (cfg.n_sweeps, 2)
    d = timeline.derive(tl)
    # The trailing 3 never-executed windows are gone: a healthy prefix
    # scores availability 1.0 instead of reading 3 phantom stalls.
    assert d["availability"]["mean"] == 1.0
    assert d["stall_windows"]["total"] == 0


def test_run_rejections():
    cfg = CFGS["raft"]
    eng = simulator.engine_def(cfg)
    with pytest.raises(ValueError, match="telemetry"):
        runner.run(cfg, eng)  # recorder without telemetry
    with pytest.raises(ValueError, match="tpu-engine"):
        dataclasses.replace(cfg, engine="cpu")
    with pytest.raises(ValueError, match=">= 0"):
        dataclasses.replace(cfg, telemetry_window=-1)
    with pytest.raises(ValueError, match="telem, win AND lat"):
        runner._chunk_jit(cfg, eng, 4,
                          runner._init_jit(cfg, eng,
                                           jnp.asarray(
                                               runner.make_seeds(cfg))),
                          jnp.int32(0),
                          win=jnp.zeros((2, 5, 7), jnp.int32))


# --- 4. bucket semantics + timeline analysis --------------------------------

def test_bucket_counts_matches_numpy_reference():
    rng = np.random.RandomState(7)
    vals = rng.randint(-5, 40000, size=(13, 9)).astype(np.int32)
    mask = rng.rand(13, 9) < 0.6
    got = np.asarray(jax.jit(flightlib.bucket_counts)(vals, mask))
    edges = list(flightlib.BUCKET_LO[1:])
    want = np.zeros(flightlib.N_BUCKETS, np.int64)
    for v in vals[mask]:
        want[np.searchsorted(edges, v, side="right")] += 1
    np.testing.assert_array_equal(got, want)
    assert got.sum() == mask.sum()
    # Edge placement: 0 -> bucket 0; 1 -> bucket 1; 2^k -> bucket k+1;
    # huge -> overflow.
    one = np.asarray(flightlib.bucket_counts(
        jnp.asarray([0, 1, 2, 4, 2 ** 14, 10 ** 9], jnp.int32), True))
    np.testing.assert_array_equal(
        np.nonzero(one)[0], [0, 1, 2, 3, 15])


def _synthetic_timeline():
    # 2 sweeps x 5 windows x 8 rounds (40 rounds). Sweep 0: crash fault
    # in window 1, commits stall in windows 1-2, recover in window 3.
    # Sweep 1: healthy throughout.
    commits = np.array([[8, 0, 0, 4, 8],
                        [8, 8, 8, 8, 8]], np.int64)
    crashes = np.array([[0, 2, 0, 0, 0],
                        [0, 0, 0, 0, 0]], np.int64)
    lat = np.zeros((2, flightlib.N_BUCKETS), np.int64)
    lat[0, [1, 3]] = [3, 1]                      # 3 at >=1, 1 at >=4
    return timeline.Timeline(
        engine="raft", window_rounds=8, n_windows=5, n_rounds=40,
        bucket_lo=flightlib.BUCKET_LO,
        windows={"entries_committed": commits, "crashes": crashes},
        latency={"election_wait_rounds": lat})


def test_timeline_derived_metrics_exact():
    tl = _synthetic_timeline()
    d = timeline.derive(tl)
    assert d["availability"]["per_sweep"] == [0.6, 1.0]
    assert d["availability"]["mean"] == 0.8
    assert d["stall_windows"] == {"per_sweep": [2, 0], "total": 2}
    assert d["commit_rate_per_round"]["overall"] == \
        pytest.approx(60 / 80)
    # Fault onset = first crash-active window; recovery = rounds from
    # its start to the end of the first committing window at/after it:
    # windows 1..3 -> 3 * 8 = 24 rounds. Sweep 1 never faults.
    assert d["fault_onset_window"] == [1, None]
    assert d["recovery_rounds"] == [24, None]
    assert d["latency"]["election_wait_rounds"] == \
        {"count": 4, "p50": 1, "p90": 4, "p99": 4}


def test_timeline_export_metrics_gauges():
    from consensus_tpu.obs import metrics as obs_metrics
    reg = obs_metrics.Registry()
    timeline.export_metrics(timeline.derive(_synthetic_timeline()),
                            registry=reg)
    snap = reg.snapshot()
    assert snap["timeline_availability_ratio"]["value"] == 0.8
    assert snap["timeline_stall_windows_total"]["value"] == 2
    assert snap["timeline_recovery_rounds_max"]["value"] == 24


def test_timeline_never_recovered_and_roundtrip():
    tl = _synthetic_timeline()
    dead = dataclasses.replace(
        tl, windows={**tl.windows,
                     "entries_committed": np.array([[8, 0, 0, 0, 0],
                                                    [8, 8, 8, 8, 8]])})
    d = timeline.derive(dead)
    assert d["recovery_rounds"][0] == -1
    # Never-recovered must be VISIBLE on a scrape (-1 sentinel), not an
    # absent gauge indistinguishable from a fault-free run.
    from consensus_tpu.obs import metrics as obs_metrics
    reg = obs_metrics.Registry()
    timeline.export_metrics(d, registry=reg)
    assert reg.snapshot()["timeline_recovery_rounds_max"]["value"] == -1
    # from_flight_dict round-trips the runner's stats["flight"] shape.
    fl = {"engine": "raft", "window_rounds": 8, "n_windows": 5,
          "n_rounds": 40, "bucket_lo": list(flightlib.BUCKET_LO),
          "windows": {k: v.tolist() for k, v in tl.windows.items()},
          "latency": {k: v.tolist() for k, v in tl.latency.items()}}
    tl2 = timeline.from_flight_dict(fl)
    assert timeline.derive(tl2) == timeline.derive(tl)
    assert "availability" in timeline.render_text(tl2,
                                                  timeline.derive(tl2))


def test_progress_counters_agree_with_timeline_layer():
    # PROGRESS_COUNTERS is derived from COMMIT_COUNTERS (one
    # declaration); what needs pinning is that the declaration covers
    # every engine and only real telemetry counter names.
    assert set(timeline.COMMIT_COUNTERS) == \
        {"raft", "raft-sparse", "pbft", "pbft-bcast", "paxos", "dpos",
         "hotstuff"}
    for name, names in timeline.COMMIT_COUNTERS.items():
        eng = simulator.engine_def(CFGS[name])
        assert set(names) <= set(eng.telemetry_names), name


# --- the ROADMAP adversary-assertion primitive ------------------------------

def test_election_disruption_run_yields_asserted_timeline():
    """A scripted election-disruption run (SPEC §6c crash adversary
    repeatedly downing nodes below quorum) must produce a timeline whose
    DERIVED metrics show the attack: availability strictly below 1 with
    stall windows, a detected fault onset, and a measured recovery —
    while a healthy run of the same protocol scores availability 1.0.
    This is the assertion primitive the adversary-scenario library
    builds on (ROADMAP)."""
    disrupted = Config(protocol="raft", n_nodes=5, n_rounds=96,
                       n_sweeps=2, log_capacity=128, max_entries=96,
                       telemetry_window=8, crash_prob=0.4,
                       recover_prob=0.15, max_crashed=3,
                       drop_rate=0.05, churn_rate=0.02)
    tl = timeline.from_flight_dict(
        _run_flight(disrupted).extras["flight"])
    d = timeline.derive(tl)
    assert d["availability"]["mean"] < 1.0
    assert d["stall_windows"]["total"] >= 1
    assert any(o is not None for o in d["fault_onset_window"])
    assert any(r is not None and r != 0 for r in d["recovery_rounds"])
    # Latency evidence of the disruption: election waits were recorded.
    assert d["latency"]["election_wait_rounds"]["count"] >= 1

    healthy = dataclasses.replace(disrupted, crash_prob=0.0,
                                  recover_prob=0.0, max_crashed=0,
                                  drop_rate=0.0, churn_rate=0.0,
                                  partition_rate=0.0)
    dh = timeline.derive(timeline.from_flight_dict(
        _run_flight(healthy).extras["flight"]))
    assert dh["availability"]["mean"] == 1.0
    assert dh["stall_windows"]["total"] == 0


# --- 5. CLI artifacts + teleview --------------------------------------------

def _load_validator():
    spec = importlib.util.spec_from_file_location(
        "validate_trace", REPO / "tools" / "validate_trace.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


CLI_FLAGS = ["--protocol", "raft", "--nodes", "5", "--rounds", "48",
             "--sweeps", "2", "--log-capacity", "16", "--max-entries", "8",
             "--drop-rate", "0.1", "--engine", "tpu", "--scan-chunk", "8",
             "--telemetry-window", "6"]


def test_cli_flight_artifacts_validate_and_teleview_renders(tmp_path,
                                                            capsys):
    from consensus_tpu import cli
    from consensus_tpu.obs import metrics as obs_metrics
    obs_metrics.reset()
    trace = tmp_path / "run.trace.jsonl"
    metrics = tmp_path / "metrics.json"
    ck = tmp_path / "ck.npz"
    # --telemetry-window implies --telemetry (no separate flag needed).
    rc = cli.main(CLI_FLAGS + ["--checkpoint", str(ck), "-v",
                               "--trace-out", str(trace),
                               "--metrics-out", str(metrics)])
    assert rc == 0
    out, err = capsys.readouterr()
    report = json.loads(out.strip().splitlines()[-1])
    assert report["flight"]["n_windows"] == 8
    assert report["telemetry"]["entries_committed"] > 0
    assert "progress: r=" in err and "eta=" in err
    # Digest neutrality through the CLI front door: the same config
    # recorder-off yields the identical digest.
    plain = run_cached(Config(protocol="raft", n_nodes=5, n_rounds=48,
                              n_sweeps=2, log_capacity=16, max_entries=8,
                              drop_rate=0.1, scan_chunk=8))
    assert report["digest"] == plain.digest
    cli_report = tmp_path / "report.json"
    cli_report.write_text(json.dumps(report))

    # The CI tripwire, exactly as CI runs it.
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "validate_trace.py"),
         "--trace", str(trace), "--metrics", str(metrics),
         "--cli-report", str(cli_report)],
        capture_output=True, text=True)
    assert proc.returncode == 0, proc.stderr

    doc = json.loads(metrics.read_text())
    assert doc["flight"]["windows"]["entries_committed"]
    assert doc["metrics"]["rounds_completed"]["value"] == 48
    assert "timeline_availability_ratio" in doc["metrics"]

    # Drift rejection: an unknown window counter + broken geometry fail.
    bad = dict(doc)
    bad["flight"] = {**doc["flight"], "n_windows": 99}
    badp = tmp_path / "bad.json"
    badp.write_text(json.dumps(bad))
    proc = subprocess.run(
        [sys.executable, str(REPO / "tools" / "validate_trace.py"),
         "--metrics", str(badp)], capture_output=True, text=True)
    assert proc.returncode == 1
    assert "n_windows" in proc.stderr

    # teleview over the metrics artifact (stays jax-free) ...
    proc = subprocess.run(
        [sys.executable, "-m", "tools.teleview",
         "--metrics", str(metrics), "--prom", str(tmp_path / "d.prom")],
        capture_output=True, text=True, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stderr
    assert "availability" in proc.stdout
    assert "timeline_availability_ratio" in \
        (tmp_path / "d.prom").read_text()

    # ... and over the recorder-on CHECKPOINT (the ring rides it).
    proc = subprocess.run(
        [sys.executable, "-m", "tools.teleview",
         "--checkpoint", str(ck), "--json"],
        capture_output=True, text=True, cwd=REPO,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert proc.returncode == 0, proc.stderr
    assert "availability" in json.loads(proc.stdout)


def test_prom_metrics_out_writes_flight_sidecar(tmp_path):
    """--metrics-out x.prom cannot embed the series in Prometheus text;
    it must land in a <stem>.flight.json sidecar teleview can load —
    not silently vanish."""
    from consensus_tpu import cli
    from consensus_tpu.obs import metrics as obs_metrics
    obs_metrics.reset()
    prom = tmp_path / "m.prom"
    assert cli.main(CLI_FLAGS + ["--metrics-out", str(prom)]) == 0
    assert "timeline_availability_ratio" in prom.read_text()
    side = tmp_path / "m.flight.json"
    tl = timeline.from_metrics_json(side)
    assert tl.n_windows == 8
    proc = subprocess.run(
        [sys.executable, "-m", "tools.teleview", "--metrics", str(side)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 0, proc.stderr
    assert "availability" in proc.stdout


def test_teleview_rejects_recorder_off_artifacts(tmp_path):
    m = tmp_path / "m.json"
    m.write_text(json.dumps({"version": 1, "metrics": {}}))
    proc = subprocess.run(
        [sys.executable, "-m", "tools.teleview", "--metrics", str(m)],
        capture_output=True, text=True, cwd=REPO)
    assert proc.returncode == 1
    assert "flight" in proc.stderr


def test_supervisor_fallback_cpu_drops_recorder_not_the_run(monkeypatch):
    """--fallback-cpu with the recorder on must DEGRADE (drop the
    digest-neutral flight series with the telemetry, as documented),
    not die on Config's rejection of telemetry_window on the cpu
    engine."""
    from consensus_tpu.network import faults
    cfg = dataclasses.replace(CFGS["raft"], partition_rate=0.0)
    base = run_cached(_off(cfg))
    real_run = simulator.run

    def tpu_down(c, **kw):
        if c.engine == "tpu":
            raise faults.InjectedTransientError("tunnel down")
        return real_run(c, **kw)

    monkeypatch.setattr(simulator, "run", tpu_down)
    res = supervisor.supervised_run(cfg, retries=1, backoff_s=0,
                                    fallback_cpu=True, telemetry=True,
                                    sleep=lambda s: None)
    rr = res.extras["run_report"]
    assert rr["fallback_used"]
    assert res.digest == base.digest
    assert "flight" not in res.extras and "telemetry" not in res.extras


def test_cli_rejects_window_on_cpu_engine_and_fsweep():
    from consensus_tpu import cli
    with pytest.raises(ValueError, match="tpu-engine"):
        cli.main(["--protocol", "raft", "--engine", "cpu",
                  "--telemetry-window", "8"])
    with pytest.raises(SystemExit):
        cli.main(["--protocol", "pbft", "--engine", "tpu",
                  "--f-sweep", "1,2", "--telemetry-window", "8"])


# --- slow tier: SIGKILL mid-run resumes to the identical series -------------

@pytest.mark.slow
def test_sigkill_midrun_resumes_to_identical_series(tmp_path):
    """A recorder-ON checkpointed CLI run is SIGKILLed after chunk 2;
    the supervised resume must reproduce BOTH the uninterrupted digest
    AND the bit-identical window ring + latency histograms — the ring
    rode the verified snapshot."""
    cfg = Config(protocol="raft", n_nodes=5, n_rounds=48, n_sweeps=2,
                 log_capacity=16, max_entries=8, scan_chunk=8,
                 drop_rate=0.1, churn_rate=0.05, telemetry_window=6)
    ck = tmp_path / "ck.npz"
    flags = ["--protocol", "raft", "--nodes", "5", "--rounds", "48",
             "--sweeps", "2", "--log-capacity", "16", "--max-entries", "8",
             "--drop-rate", "0.1", "--churn-rate", "0.05",
             "--engine", "tpu", "--platform", "cpu", "--scan-chunk", "8",
             "--telemetry-window", "6", "--checkpoint", str(ck)]
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               **{faults.ENV_VAR: json.dumps({"kill_after_chunk": 2})})
    p = subprocess.run([sys.executable, "-m", "consensus_tpu"] + flags,
                       capture_output=True, text=True, env=env,
                       cwd=REPO, timeout=600)
    assert p.returncode == -signal.SIGKILL, (p.returncode, p.stderr)
    assert runner.peek_checkpoint(ck, cfg) == 16

    base = _run_flight(dataclasses.replace(cfg, scan_chunk=0,
                                           telemetry_window=6))
    res = supervisor.supervised_run(cfg, checkpoint_path=str(ck),
                                    retries=0, telemetry=True)
    assert res.digest == base.digest
    assert res.extras["run_report"]["resumed_from_round"] == 16
    for k, v in base.extras["flight"]["windows"].items():
        np.testing.assert_array_equal(
            res.extras["flight"]["windows"][k], v, err_msg=k)
    for k, v in base.extras["flight"]["latency"].items():
        np.testing.assert_array_equal(
            res.extras["flight"]["latency"][k], v, err_msg=k)
