"""Shared test helpers: cache simulator runs so differential and invariant
tests over the same Config don't recompute (and skip the timing warmup —
tests assert on decided logs, not steady-state throughput)."""
import functools

from consensus_tpu.network import simulator


@functools.lru_cache(maxsize=None)
def run_cached(cfg):
    return simulator.run(cfg, warmup=False)
