"""Shared test helpers: cache simulator runs so differential and invariant
tests over the same Config don't recompute (and skip the timing warmup —
tests assert on decided logs, not steady-state throughput)."""
import functools

from consensus_tpu.network import simulator


@functools.lru_cache(maxsize=None)
def run_cached(cfg):
    return simulator.run(cfg, warmup=False)


def committed_prefixes_agree(res, nodes, sweep) -> bool:
    """True iff every pair of ``nodes``' committed prefixes agrees in
    ``sweep`` (State-Machine Safety over a RunResult's decided records)."""
    import numpy as np

    for a, i in enumerate(nodes):
        for j in nodes[a + 1:]:
            c = int(min(res.counts[sweep, i], res.counts[sweep, j]))
            if c > 0 and (
                    not np.array_equal(res.rec_a[sweep, i, :c],
                                       res.rec_a[sweep, j, :c])
                    or not np.array_equal(res.rec_b[sweep, i, :c],
                                          res.rec_b[sweep, j, :c])):
                return False
    return True


@functools.lru_cache(maxsize=None)
def trace_raft_rounds(cfg, sweep: int | None = 0):
    """Per-round {role, term, commit, log_term, log_val} numpy arrays for
    round-granular invariant checks (Election Safety / Leader Completeness
    need per-term winners and commit timing, which final states cannot
    reconstruct). Shapes are [R, ...] for a single ``sweep``, or
    [R, B, ...] over all sweeps with ``sweep=None``. Uses the dense SPEC §3
    kernel with the runner's per-sweep seed derivation (lo32(seed + b))."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from consensus_tpu.engines.raft import raft_init, raft_round
    from consensus_tpu.network.runner import make_seeds

    assert cfg.max_active == 0, "trace helper uses the dense engine"

    def go(seed):
        def body(c, r):
            c2 = raft_round(cfg, c, r)
            return c2, (c2.role, c2.term, c2.commit, c2.log_term, c2.log_val,
                        c2.down)
        _, out = jax.lax.scan(body, raft_init(cfg, seed),
                              jnp.arange(cfg.n_rounds, dtype=jnp.int32))
        return out

    seeds = make_seeds(cfg)
    if sweep is None:
        out = jax.jit(jax.vmap(go, in_axes=0, out_axes=1))(jnp.asarray(seeds))
    else:
        out = jax.jit(go)(seeds[sweep])
    role, term, commit, log_term, log_val, down = out
    return {"role": np.asarray(role), "term": np.asarray(term),
            "commit": np.asarray(commit), "log_term": np.asarray(log_term),
            "log_val": np.asarray(log_val), "down": np.asarray(down)}
