"""Coverage-guided adversary search (tools/advsearch) + the traced-knob
generation batching underneath it (core/knobs, runner.run_knob_batch).

Five contracts under test, per the PR's acceptance criteria:

  1. **Lane == production run, bit-for-bit** — a knob-batch lane whose
     traced knob row equals a real Config's cutoffs computes the
     identical trajectory (flight series AND decided logs) as a plain
     ``runner.run`` of that config. This is what makes findings
     replayable and distilled scenarios faithful.
  2. **One compiled program per generation per (protocol, shape)** —
     the fixed-budget smoke search's trace carries exactly one
     ``dispatch`` span per generation (no per-candidate recompile).
  3. **Determinism / crash-safe resume** — same search seed ⇒
     identical generation sequence, coverage map and findings; a
     search interrupted between (or mid-) generations resumes from the
     state file to the same findings.
  4. **Knob-fuzz: no silently-ignored combination** — randomly
     composed adversary knob dicts either validate into a Config or
     raise ValueError, never anything else and never silently drop a
     knob (the PR 10 discipline extended to the whole cross-product
     the search explores).
  5. **Distilled catalog** — the committed discovered scenario loads
     into the library, carries a schema-valid embedded finding, and
     passes its TimelineBounds through the real ``--scenario`` front
     door (its oracle digest is pinned in the catalog).
"""
import dataclasses
import json
import pathlib
import random

import numpy as np
import pytest

from consensus_tpu import scenarios
from consensus_tpu.core import knobs
from consensus_tpu.core.config import Config
from consensus_tpu.network import runner, simulator

from tools.advsearch import search as advsearch
from tools import validate_trace

REPO = pathlib.Path(__file__).resolve().parents[1]


def _row(cfg):
    return [int(getattr(cfg, n)) for n in knobs.KNOB_COLUMNS]


# --- 1. lane == production run ----------------------------------------------

LANE_CASES = {
    "dpos": (
        Config(protocol="dpos", n_nodes=24, n_rounds=64, n_sweeps=2,
               log_capacity=96, n_candidates=12, n_producers=6, seed=11,
               drop_rate=0.4, miss_rate=0.2, max_delay_rounds=4,
               telemetry_window=4),
        dict(drop_rate=0.1, miss_rate=0.05)),
    "raft": (
        Config(protocol="raft", n_nodes=7, n_rounds=64, n_sweeps=2,
               log_capacity=32, max_entries=24, seed=11, drop_rate=0.3,
               partition_rate=0.2, churn_rate=0.05, crash_prob=0.1,
               recover_prob=0.3, max_delay_rounds=4, telemetry_window=4),
        dict(drop_rate=0.55, crash_prob=0.02, partition_rate=0.0)),
    "pbft": (
        Config(protocol="pbft", f=2, n_nodes=7, n_rounds=64, n_sweeps=2,
               log_capacity=64, seed=11, drop_rate=0.3,
               partition_rate=0.15, churn_rate=0.03, crash_prob=0.1,
               recover_prob=0.3, telemetry_window=4),
        dict(drop_rate=0.45, churn_rate=0.1)),
    "paxos": (
        Config(protocol="paxos", n_nodes=9, n_rounds=64, n_sweeps=2,
               log_capacity=64, seed=11, drop_rate=0.3,
               partition_rate=0.15, churn_rate=0.03, crash_prob=0.1,
               recover_prob=0.3, telemetry_window=4),
        dict(drop_rate=0.5, crash_prob=0.25, recover_prob=0.1)),
}


@pytest.mark.parametrize("name", sorted(LANE_CASES))
def test_knob_batch_lane_bit_identical_to_production_run(name):
    """Tentpole soundness: per engine, each vmap lane of the one
    compiled generation program — knob cutoffs as traced operands —
    reproduces the plain per-config run bit-for-bit: every flight
    window series AND every decided-log extract leaf. A lane that
    zeroes a gated-on knob (partition_rate=0 under a partition-on base)
    must equal the knob-off config's run."""
    base, variant = LANE_CASES[name]
    eng = simulator.engine_def(base)
    seeds = runner.make_seeds(base)
    cfgs = [base, dataclasses.replace(base, **variant)]
    kmat = np.array([_row(c) for c in cfgs], np.uint32)
    out, flight = runner.run_knob_batch(base, eng, seeds, kmat)
    for i, cfg in enumerate(cfgs):
        stats: dict = {}
        ref = runner.run(
            dataclasses.replace(cfg, n_sweeps=1, seed=int(seeds[i])),
            eng, stats=stats, telemetry=True)
        for cname, v in flight["windows"].items():
            np.testing.assert_array_equal(
                v[i], stats["flight"]["windows"][cname][0],
                err_msg=f"lane {i} window {cname}")
        for k in ref:
            np.testing.assert_array_equal(out[k][i], ref[k][0],
                                          err_msg=f"lane {i} {k}")


def test_knob_batch_usage_errors():
    base, _ = LANE_CASES["raft"]
    eng = simulator.engine_def(base)
    seeds = runner.make_seeds(base)
    kmat = np.array([_row(base)] * 2, np.uint32)
    with pytest.raises(ValueError, match="telemetry_window"):
        runner.run_knob_batch(
            dataclasses.replace(base, telemetry_window=0), eng, seeds,
            kmat)
    with pytest.raises(ValueError, match="KNOB_COLUMNS"):
        runner.run_knob_batch(base, eng, seeds, kmat[:, :3])
    with pytest.raises(ValueError, match="n_sweeps"):
        runner.run_knob_batch(base, eng, seeds[:1], kmat[:1])
    # A lane varying a knob the base gates OFF would be silently
    # ignored — rejected instead (miss_rate on a raft base).
    bad = kmat.copy()
    bad[1, list(knobs.KNOB_COLUMNS).index("miss_cutoff")] = 12345
    with pytest.raises(ValueError, match="miss_cutoff"):
        runner.run_knob_batch(base, eng, seeds, bad)


def test_knob_view_rejects_unknown_knob():
    base, _ = LANE_CASES["raft"]
    with pytest.raises(ValueError, match="unknown traced knobs"):
        knobs.KnobView(base, n_rounds=5)
    view = knobs.KnobView(base, drop_cutoff=7)
    assert view.drop_cutoff == 7
    assert view.churn_cutoff == base.churn_cutoff   # untraced: static
    assert view.n_nodes == base.n_nodes             # delegated
    assert view.crash_on is True                    # gate from base


# --- 2/3. search determinism + resume ---------------------------------------

_TINY = dict(search_seed=123, generations=3, population=4, confirm=False)


def _space():
    # The smoke space at a reduced rounds budget for tier-1 speed.
    sp = advsearch.SPACES["dpos-delivery"]
    return dataclasses.replace(
        sp, name="tiny-dpos", base=dataclasses.replace(sp.base,
                                                       n_rounds=64))


def test_search_same_seed_identical_findings(tmp_path, monkeypatch):
    monkeypatch.setitem(advsearch.SPACES, "tiny-dpos", _space())
    a = advsearch.run_search(advsearch.SPACES["tiny-dpos"], **_TINY)
    b = advsearch.run_search(advsearch.SPACES["tiny-dpos"], **_TINY)
    assert a.to_doc() == b.to_doc()
    # ... and a different seed explores a different population.
    c = advsearch.run_search(advsearch.SPACES["tiny-dpos"],
                             **{**_TINY, "search_seed": 124})
    assert c.last_eval[0]["knobs"] != a.last_eval[0]["knobs"]


def test_search_resume_from_state_converges_to_same_findings(
        tmp_path, monkeypatch):
    """Crash-safe resume: a search stopped after generation 1 (its
    state file is the per-generation manifest) resumes and finishes
    with EXACTLY the uninterrupted run's state — populations, coverage
    map, findings, history."""
    monkeypatch.setitem(advsearch.SPACES, "tiny-dpos", _space())
    sp = advsearch.SPACES["tiny-dpos"]
    full = advsearch.run_search(sp, state_dir=tmp_path / "full", **_TINY)
    part = advsearch.run_search(sp, state_dir=tmp_path / "p",
                                **{**_TINY, "generations": 2})
    assert part.generations_done == 2
    resumed = advsearch.run_search(sp, state_dir=tmp_path / "p",
                                   resume=True, **_TINY)
    assert resumed.to_doc() == full.to_doc()
    # Foreign state identity is refused, not silently restarted —
    # including a changed fitness parameter (budget_weight shapes every
    # generation's elite selection; splicing weights would produce a
    # population no single run can reproduce).
    with pytest.raises(ValueError, match="different search"):
        advsearch.run_search(sp, state_dir=tmp_path / "p", resume=True,
                             **{**_TINY, "search_seed": 999})
    with pytest.raises(ValueError, match="different search"):
        advsearch.run_search(sp, state_dir=tmp_path / "p", resume=True,
                             budget_weight=2.0, **_TINY)


def test_search_population_derivation_is_pure():
    sp = advsearch.SPACES["raft-elections"]
    prev = [{"candidate": c, "knobs": advsearch._fresh(sp, 5, 0, c),
             "fitness": float(c), "novel": c == 2}
            for c in range(6)]
    p1 = advsearch.next_population(sp, 5, 1, 6, prev)
    p2 = advsearch.next_population(sp, 5, 1, 6, prev)
    assert p1 == p2
    for cand in p1:
        for k in sp.knobs:
            assert k.lo <= cand[k.field] <= k.hi


# --- 4. knob-fuzz: validate cleanly or raise ValueError ---------------------

# Every adversary-facing Config knob the search (or a user) may
# compose, with generators spanning valid AND invalid values.
_FUZZ_FIELDS = {
    "protocol": lambda r: r.choice(["raft", "pbft", "paxos", "dpos",
                                    "hotstuff"]),
    "engine": lambda r: r.choice(["cpu", "tpu"]),
    # The SPEC §7b engine's shape/pacemaker fields (shared with pbft):
    # fuzzed so hotstuff's byz-mode/shape cross-rules are exercised too.
    "f": lambda r: r.choice([1, 2]),
    "view_timeout": lambda r: r.choice([2, 8]),
    "drop_rate": lambda r: r.choice([0.0, 0.3, 1.0]),
    "partition_rate": lambda r: r.choice([0.0, 0.25, 1.0]),
    "churn_rate": lambda r: r.choice([0.0, 0.1]),
    "crash_prob": lambda r: r.choice([0.0, 0.2]),
    "recover_prob": lambda r: r.choice([0.0, 0.4]),
    "max_crashed": lambda r: r.choice([0, 2, 100]),
    "miss_rate": lambda r: r.choice([0.0, 0.2]),
    "max_delay_rounds": lambda r: r.choice([0, 4, 16, 17, -1]),
    "attack": lambda r: r.choice(["none", "elect", "sticky", "bogus"]),
    "attack_rate": lambda r: r.choice([1.0, 0.5]),
    "attack_target": lambda r: r.choice([0, 3, -2, 99]),
    "n_byzantine": lambda r: r.choice([0, 1, 50]),
    "byz_mode": lambda r: r.choice(["silent", "equivocate"]),
    "fault_model": lambda r: r.choice(["edge", "bcast"]),
    "telemetry_window": lambda r: r.choice([0, 4]),
}


def test_knob_fuzz_config_validates_or_raises_value_error():
    """Property test over the adversary knob cross-product: every
    randomly composed combination either builds a Config (whose knobs
    then round-trip through to_json — nothing silently dropped) or
    raises ValueError with a message naming a field. Any OTHER
    exception is a validation hole."""
    rng = random.Random(20260803)
    built = rejected = 0
    for _ in range(400):
        kw = {name: gen(rng) for name, gen in _FUZZ_FIELDS.items()
              if rng.random() < 0.6}
        if kw.get("protocol") in ("pbft", "hotstuff"):
            # Keep the shape constraint orthogonal to the knob fuzz
            # (n_nodes == 3f+1 is a shape rule, not an adversary knob).
            kw["n_nodes"] = 3 * kw.get("f", 1) + 1
        try:
            cfg = Config(**kw)
        except ValueError as exc:
            rejected += 1
            assert str(exc), "ValueError must carry a message"
            continue
        built += 1
        d = json.loads(cfg.to_json())
        for name, v in kw.items():
            assert d[name] == v, f"{name} silently altered"
        assert Config.from_json(cfg.to_json()) == cfg
    # The generators must actually exercise both outcomes (most random
    # compositions trip a cross-field rule — that asymmetry is the
    # no-silent-ignores discipline doing its job, and it widened with
    # the hotstuff surface: two of five protocols are now BFT shapes
    # that additionally reject equivocate/bcast/miss/attack combos).
    assert built > 12 and rejected > 100, (built, rejected)


def test_space_definitions_are_gate_representative():
    """Every curated space's base really gates ON each searched knob
    (run_knob_batch would reject the kmat otherwise) and stays within
    the oracle-replay N <= 2k budget."""
    for sp in advsearch.SPACES.values():
        gates = {"crash_prob": sp.base.crash_on,
                 "recover_prob": sp.base.crash_on,
                 "miss_rate": sp.base.miss_on,
                 "partition_rate": not sp.base.no_partition,
                 "attack_rate": sp.base.attack != "none",
                 "agg_poison_rate": sp.base.agg_poison_on,
                 "byz_uplink_rate": sp.base.uplink_lies_on}
        for k in sp.knobs:
            assert gates.get(k.field, True), (sp.name, k.field)
        assert sp.base.n_nodes <= 2048
        # Commit supply outlives the run (fitness-signal hygiene).
        if sp.base.protocol == "raft":
            assert sp.base.max_entries >= sp.base.n_rounds
        elif sp.base.protocol in ("pbft", "paxos", "dpos", "hotstuff"):
            assert sp.base.log_capacity >= sp.base.n_rounds


# --- finding schema: producer <-> validator sync ----------------------------

def test_finding_fields_match_validator_registry():
    assert set(advsearch.FINDING_FIELDS) == validate_trace.FINDING_FIELDS


def test_findings_artifact_schema_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setitem(advsearch.SPACES, "tiny-dpos", _space())
    st = advsearch.run_search(advsearch.SPACES["tiny-dpos"], **_TINY)
    doc = {"version": 1, "space": st.space,
           "search_seed": st.search_seed,
           "generations": st.generations_done, "findings": st.findings}
    assert validate_trace.validate_finding_doc("mem", doc) == []
    p = tmp_path / "findings.json"
    p.write_text(json.dumps(doc))
    assert validate_trace.validate_finding(p) == []
    # A drifted key fails loudly.
    if st.findings:
        bad = json.loads(json.dumps(doc))
        bad["findings"][0]["surprise"] = 1
        assert any("surprise" in e for e in
                   validate_trace.validate_finding_doc("mem", bad))


def test_oracle_confirm_replays_byte_equal():
    sp = _space()
    res = advsearch._confirm(sp, dict(miss_rate=0.2, drop_rate=0.4,
                                      churn_rate=0.02), seed=99)
    assert res["confirmed"] is True
    assert len(res["digest"]) == 64
    # Unmirrored spaces cannot confirm — recorded, not guessed.
    atk = advsearch.SPACES["raft-attack-elect"]
    assert advsearch._confirm(atk, dict(attack_rate=0.5), seed=1) == \
        {"confirmed": None, "reason": "tpu-only"}


def test_attack_report_routes_unmirrored_findings(tmp_path):
    """§A.3 attack-space findings cannot be oracle-confirmed, so they
    bypass the distilled catalog and land in the standalone
    attack-findings report instead: distill refuses with a pointer at
    the report path, write_attack_report round-trips the finding schema
    and replaces entries keyed by (space, seed)."""
    atk = advsearch.SPACES["raft-attack-elect"]
    finding = {
        "schema": 1, "space": atk.name, "protocol": "raft",
        "generation": 0, "candidate": 0, "eval_seed": 1,
        "knobs": {"attack_rate": 0.5, "drop_rate": 0.1}, "budget": 0.3,
        "severity": 0.5, "fitness": 0.35,
        "metrics": {"availability": 0.5, "stall_ratio": 0.2,
                    "stall_windows": 2, "never_recovered": False,
                    "recovery_rounds": 8},
        "coverage_key": "a5s2n0l-",
        "oracle": {"confirmed": None, "reason": "tpu-only"}}
    st = advsearch.SearchState(space=atk.name, search_seed=7,
                               population=4, generations_done=2,
                               findings=[finding])
    with pytest.raises(ValueError, match="report"):
        advsearch.distill(st, 0, "x")
    out = tmp_path / "attack_findings.json"
    entry = advsearch.write_attack_report(st, out)
    assert entry["mirrored"] is False
    doc = json.loads(out.read_text())
    assert len(doc["reports"]) == 1
    assert doc["reports"][0]["findings"][0]["knobs"]["attack_rate"] == 0.5
    # Same (space, seed) replaces; a different seed appends.
    advsearch.write_attack_report(st, out)
    assert len(json.loads(out.read_text())["reports"]) == 1
    st2 = dataclasses.replace(st, search_seed=8)
    advsearch.write_attack_report(st2, out)
    assert len(json.loads(out.read_text())["reports"]) == 2
    # The findings inside obey the validator's finding schema.
    errs = validate_trace.validate_finding_doc("rep", {
        "version": 1, "space": st.space, "search_seed": 7,
        "generations": 2, "findings": st.findings})
    assert errs == []


def test_committed_attack_report_schema_valid():
    """The committed §A.3 report artifact (benchmarks/parts/
    attack_findings.json) holds only unmirrored-space findings with
    explicit unconfirmed-oracle provenance, schema-checked."""
    path = REPO / "benchmarks/parts/attack_findings.json"
    assert path.exists(), "attack_findings.json missing"
    doc = json.loads(path.read_text())
    assert doc["version"] == advsearch.ATTACK_REPORT_VERSION
    assert doc["reports"]
    for rep in doc["reports"]:
        assert rep["mirrored"] is False
        assert rep["findings"], "a committed report must carry findings"
        for f in rep["findings"]:
            assert f["oracle"]["confirmed"] is None
            assert f["oracle"]["reason"] == "tpu-only"
        errs = validate_trace.validate_finding_doc("committed", {
            "version": 1, "space": rep["space"],
            "search_seed": rep["search_seed"],
            "generations": rep["generations"],
            "findings": rep["findings"]})
        assert errs == []


# --- 5. the committed discovered catalog ------------------------------------

CATALOG = REPO / "consensus_tpu/scenarios/discovered.json"


def test_discovered_catalog_registered_and_schema_valid():
    """The committed catalog (the PR's discovered scenario) loads into
    the scenario library, embeds a schema-valid oracle-CONFIRMED
    finding, and names no hand-built scenario."""
    assert CATALOG.exists(), "discovered.json missing"
    doc = json.loads(CATALOG.read_text())
    assert doc["scenarios"], "catalog is empty"
    for entry in doc["scenarios"]:
        s = entry["scenario"]
        assert s["name"] in scenarios.DISCOVERED
        assert s["name"] in scenarios.SCENARIOS
        reg = scenarios.get(s["name"])
        assert reg.protocol == s["protocol"]
        assert dict(reg.overrides) == dict(s["overrides"])
        f = entry["finding"]
        errs = validate_trace.validate_finding_doc("catalog", {
            "version": 1, "space": f["space"],
            "search_seed": 0, "generations": f["generation"] + 1,
            "findings": [f]})
        assert errs == [], errs
        assert f["oracle"]["confirmed"] is True
        # The searched knobs survive verbatim into the overrides —
        # the scenario replays the finding, not an approximation.
        for k, v in f["knobs"].items():
            assert s["overrides"][k] == v
    # Hand-built names stay hand-built.
    hand = set(scenarios.SCENARIOS) - set(scenarios.DISCOVERED)
    assert {e["scenario"]["name"] for e in doc["scenarios"]} \
        .isdisjoint(hand)


def test_discovered_scenario_passes_bounds_via_cli(capsys):
    """Acceptance: the discovered scenario runs through the real
    ``--scenario`` front door at its tuned shape and PASSES its
    TimelineBounds (exit 0, verdict embedded in the report)."""
    from consensus_tpu import cli
    name = next(iter(scenarios.DISCOVERED))
    tuned = scenarios.get(name).tuned
    rc = cli.main(["--scenario", name,
                   "--nodes", str(tuned["n_nodes"]),
                   "--rounds", str(tuned["n_rounds"]),
                   "--log-capacity", str(tuned["log_capacity"]),
                   "--max-entries", str(tuned["max_entries"]),
                   "--sweeps", "2", "--seed", "11", "--platform", "cpu"])
    assert rc == 0
    out = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert out["scenario"]["name"] == name
    assert out["scenario"]["passed"] is True


def test_discovered_scenario_differs_from_hand_library():
    """The discovery is NEW: no hand-built scenario scripts the same
    (protocol, adversary-knob) composition."""
    for name in scenarios.DISCOVERED:
        d = scenarios.get(name)
        knob_keys = {k for k in d.overrides
                     if k in advsearch.RATE_CUTOFFS}
        for hname in set(scenarios.SCENARIOS) - set(scenarios.DISCOVERED):
            h = scenarios.get(hname)
            assert (h.protocol, {k: h.overrides.get(k)
                                 for k in knob_keys}) \
                != (d.protocol, {k: d.overrides.get(k)
                                 for k in knob_keys})


# --- SIGKILL mid-search resume (slow tier) ----------------------------------

@pytest.mark.slow
def test_sigkill_mid_search_resumes_to_same_findings(tmp_path):
    """Acceptance: a real SIGKILL mid-search (delivered as soon as the
    per-generation state manifest records progress, i.e. somewhere
    inside a later generation's evaluation) loses at most the
    interrupted generation; --resume recomputes it from the recorded
    prefix — pure counter-RNG — and the final state equals the
    uninterrupted run's, finding-for-finding."""
    import os
    import signal
    import subprocess
    import sys
    import time

    args = ["--space", "dpos-delivery", "--seed", "123",
            "--generations", "3", "--population", "4", "--no-confirm"]
    state = tmp_path / "st"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    p = subprocess.Popen(
        [sys.executable, "-m", "tools.advsearch", "search",
         "--state-dir", str(state)] + args,
        env=env, cwd=REPO, stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL)
    sf = advsearch.state_path(state)
    deadline = time.monotonic() + 300
    try:
        while time.monotonic() < deadline:
            if sf.exists() and \
                    json.loads(sf.read_text())["generations_done"] >= 1:
                break
            if p.poll() is not None:
                pytest.fail("search exited before writing generation-1 "
                            "state")
            time.sleep(0.05)
        else:
            pytest.fail("search never wrote generation-1 state")
        p.send_signal(signal.SIGKILL)
    finally:
        p.wait(timeout=60)
    assert p.returncode == -signal.SIGKILL
    done = json.loads(sf.read_text())["generations_done"]
    assert 1 <= done <= 3

    p2 = subprocess.run(
        [sys.executable, "-m", "tools.advsearch", "search",
         "--state-dir", str(state), "--resume"] + args,
        env=env, cwd=REPO, capture_output=True, text=True, timeout=300)
    assert p2.returncode == 0, p2.stderr

    base = advsearch.run_search(advsearch.SPACES["dpos-delivery"],
                                search_seed=123, generations=3,
                                population=4, confirm=False)
    resumed = json.loads(sf.read_text())
    assert resumed == base.to_doc()


def test_smoke_gate_in_process():
    """Tier-1 mirror of `make check`'s advsearch layer (same SMOKE
    budget verbatim — the two cannot drift): the fixed-budget search
    must witness one `dispatch` span per generation on its own trace
    and produce a schema-clean findings doc."""
    from tools.advsearch import __main__ as advcli
    assert advcli.main(["smoke"]) == 0
