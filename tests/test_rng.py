"""Threefry parity: numpy twin == jnp twin == JAX's own threefry2x32."""
import numpy as np

from consensus_tpu.core import rng


def test_numpy_matches_jax_internal():
    # jax._src.prng.threefry_2x32 is the battle-tested reference.
    from jax._src import prng as jax_prng

    r = np.random.RandomState(0)
    for _ in range(20):
        k = r.randint(0, 2**32, size=2, dtype=np.uint32)
        c = r.randint(0, 2**32, size=2, dtype=np.uint32)
        ours0, ours1 = rng.threefry2x32_np(k[0], k[1], c[0], c[1])
        theirs = jax_prng.threefry_2x32(np.array(k), np.array(c))
        assert np.uint32(theirs[0]) == ours0, (k, c)
        assert np.uint32(theirs[1]) == ours1, (k, c)


def test_numpy_matches_jnp_vectorized():
    k0 = np.uint32(0xDEADBEEF)
    k1 = np.uint32(0x12345678)
    c0 = np.arange(1000, dtype=np.uint32)
    c1 = np.arange(1000, dtype=np.uint32)[::-1].copy()
    n0, n1 = rng.threefry2x32_np(k0, k1, c0, c1)
    j0, j1 = rng.threefry2x32_jnp(k0, k1, c0, c1)
    np.testing.assert_array_equal(n0, np.asarray(j0))
    np.testing.assert_array_equal(n1, np.asarray(j1))


def test_random_u32_streams_disjoint_and_deterministic():
    ar = np.arange(100, dtype=np.uint32)
    a = rng.random_u32_np(42, rng.STREAM_DELIVER, 7, 0, ar)
    b = rng.random_u32_np(42, rng.STREAM_DELIVER, 7, 0, ar)
    c = rng.random_u32_np(42, rng.STREAM_TIMEOUT, 7, 0, ar)
    np.testing.assert_array_equal(a, b)
    assert (a != c).any()


def test_random_u32_jnp_matches_np():
    i = np.arange(8, dtype=np.uint32)[:, None]
    j = np.arange(8, dtype=np.uint32)[None, :]
    a = rng.random_u32_np(123456789, rng.STREAM_DELIVER, 3, i, j)
    b = rng.random_u32_jnp(np.uint32(123456789), rng.STREAM_DELIVER, 3, i, j)
    np.testing.assert_array_equal(a, np.asarray(b))


def test_prob_threshold():
    assert rng.prob_threshold_u32(0.0) == 0
    assert rng.prob_threshold_u32(1.0) == 0xFFFFFFFF
    assert rng.prob_threshold_u32(0.5) == 2**31
