"""Threefry parity: numpy twin == jnp twin == JAX's own threefry2x32."""
import numpy as np

from consensus_tpu.core import rng


def test_numpy_matches_jax_internal():
    # jax._src.prng.threefry_2x32 is the battle-tested reference.
    from jax._src import prng as jax_prng

    r = np.random.RandomState(0)
    for _ in range(20):
        k = r.randint(0, 2**32, size=2, dtype=np.uint32)
        c = r.randint(0, 2**32, size=2, dtype=np.uint32)
        ours0, ours1 = rng.threefry2x32_np(k[0], k[1], c[0], c[1])
        theirs = jax_prng.threefry_2x32(np.array(k), np.array(c))
        assert np.uint32(theirs[0]) == ours0, (k, c)
        assert np.uint32(theirs[1]) == ours1, (k, c)


def test_numpy_matches_jnp_vectorized():
    k0 = np.uint32(0xDEADBEEF)
    k1 = np.uint32(0x12345678)
    c0 = np.arange(1000, dtype=np.uint32)
    c1 = np.arange(1000, dtype=np.uint32)[::-1].copy()
    n0, n1 = rng.threefry2x32_np(k0, k1, c0, c1)
    j0, j1 = rng.threefry2x32_jnp(k0, k1, c0, c1)
    np.testing.assert_array_equal(n0, np.asarray(j0))
    np.testing.assert_array_equal(n1, np.asarray(j1))


def test_random_u32_streams_disjoint_and_deterministic():
    ar = np.arange(100, dtype=np.uint32)
    a = rng.random_u32_np(42, rng.STREAM_DELIVER, 7, 0, ar)
    b = rng.random_u32_np(42, rng.STREAM_DELIVER, 7, 0, ar)
    c = rng.random_u32_np(42, rng.STREAM_TIMEOUT, 7, 0, ar)
    np.testing.assert_array_equal(a, b)
    assert (a != c).any()


def test_random_u32_jnp_matches_np():
    i = np.arange(8, dtype=np.uint32)[:, None]
    j = np.arange(8, dtype=np.uint32)[None, :]
    a = rng.random_u32_np(123456789, rng.STREAM_DELIVER, 3, i, j)
    b = rng.random_u32_jnp(np.uint32(123456789), rng.STREAM_DELIVER, 3, i, j)
    np.testing.assert_array_equal(a, np.asarray(b))


def test_prob_threshold():
    assert rng.prob_threshold_u32(0.0) == 0
    assert rng.prob_threshold_u32(1.0) == 0xFFFFFFFF
    assert rng.prob_threshold_u32(0.5) == 2**31


# --- SPEC §2 delivery mixer --------------------------------------------------

def test_delivery_mixer_jnp_matches_np():
    i = np.arange(64, dtype=np.uint32)[:, None]
    j = np.arange(64, dtype=np.uint32)[None, :]
    for seed, r in [(0, 0), (42, 7), (0xFFFFFFFF, 1023)]:
        a = rng.delivery_u32_np(seed, r, i, j)
        b = rng.delivery_u32_jnp(np.uint32(seed), np.uint32(r), i, j)
        np.testing.assert_array_equal(a, np.asarray(b))


def test_delivery_mixer_deterministic_and_seed_sensitive():
    j = np.arange(1000, dtype=np.uint32)
    a = rng.delivery_u32_np(42, 3, 5, j)
    b = rng.delivery_u32_np(42, 3, 5, j)
    c = rng.delivery_u32_np(43, 3, 5, j)
    np.testing.assert_array_equal(a, b)
    assert (a != c).any()


def test_delivery_mixer_avalanche():
    """Murmur-finalizer quality check: flipping one input bit flips ~half
    the output bits, and the per-bit one-density over many draws is ~0.5.
    Guards against a future edit quietly degrading the mixer into
    something whose bias would distort every drop decision."""
    r = np.random.RandomState(1)
    n = 2000
    seeds = r.randint(0, 2**32, size=n).astype(np.uint32)
    rounds = r.randint(0, 2**20, size=n).astype(np.uint32)
    i = r.randint(0, 2**17, size=n).astype(np.uint32)
    j = r.randint(0, 2**17, size=n).astype(np.uint32)
    base = rng.delivery_u32_np(seeds, rounds, i, j)
    # per-output-bit balance
    bits = ((base[:, None] >> np.arange(32)) & 1).mean(axis=0)
    assert (np.abs(bits - 0.5) < 0.06).all(), bits
    # avalanche on the seed key and each of the three absorbed inputs
    for flipped in (rng.delivery_u32_np(seeds ^ np.uint32(2), rounds, i, j),
                    rng.delivery_u32_np(seeds, rounds ^ np.uint32(1), i, j),
                    rng.delivery_u32_np(seeds, rounds, i ^ np.uint32(64), j),
                    rng.delivery_u32_np(seeds, rounds, i, j ^ np.uint32(1 << 16))):
        ham = np.unpackbits((base ^ flipped).view(np.uint8)).sum() / n
        assert 13.0 < ham < 19.0, ham  # ideal 16


def test_delivery_mixer_dense_lattice_statistics():
    """The delivery mixer feeds a DENSE (round, src, dst) integer lattice —
    exactly the regime where non-cryptographic mixers show structured
    correlations that a single-bit avalanche test cannot see (ADVICE r4).
    Deterministic lattice, 1M draws; bounds are ~5 sigma, so a pass is
    stable and a structural regression (dropping an absorb, weakening the
    finalizer) blows the chi-squares by orders of magnitude."""
    N, R = 256, 16
    r = np.arange(R, dtype=np.uint32)[:, None, None]
    i = np.arange(N, dtype=np.uint32)[None, :, None]
    j = np.arange(N, dtype=np.uint32)[None, None, :]
    d = rng.delivery_u32_np(np.uint32(42), r, i, j)  # [R, N, N]

    # Uniformity: chi-square of the top byte over 256 buckets (~chi2(255),
    # mean 255, std ~22.6). Measured 251.6.
    cnt = np.bincount((d >> np.uint32(24)).ravel(), minlength=256)
    E = d.size / 256
    chi = ((cnt - E) ** 2 / E).sum()
    assert 150 < chi < 370, chi

    # Drop counts at the SPEC §2 cutoff comparison, p=0.25: per-row and
    # per-column counts are Binomial(N, p); their z-square sums are
    # ~chi2(R*N) (mean 4096, std ~90.5). Measured 4010 / 4071.
    cut = np.uint32(rng.prob_threshold_u32(0.25))
    b = d < cut
    for ax in (2, 1):
        c = b.sum(axis=ax)
        z = (c - N * 0.25) / np.sqrt(N * 0.25 * 0.75)
        assert 3650 < (z ** 2).sum() < 4550, (ax, (z ** 2).sum())
        assert np.abs(z).max() < 5.5, (ax, np.abs(z).max())

    # Pairwise structure: adjacent-edge, adjacent-round, and transposed
    # (i<->j) drop bits must be uncorrelated (1M samples => se ~1e-3;
    # measured |corr| <= 0.003 on all four).
    b5 = (d < np.uint32(rng.prob_threshold_u32(0.5))).astype(np.float64)
    for a, bb in ((b5[:, :, :-1], b5[:, :, 1:]),
                  (b5[:, :-1, :], b5[:, 1:, :]),
                  (b5[:-1], b5[1:]),
                  (b5, np.swapaxes(b5, 1, 2))):
        corr = np.corrcoef(a.ravel(), bb.ravel())[0, 1]
        assert abs(corr) < 0.01, corr
