"""SPEC §6c crash-recover adversary: per-node persistent/volatile state
split across all six engines.

Three contracts under test, per the acceptance criteria:

  1. **Digest neutrality off** — `crash_prob = 0` must not perturb any
     existing digest, for every engine, including scan_chunk /
     sweep_chunk execution strategies (the crash block is a static
     no-op when the cutoff is 0).
  2. **Durability on** — with `crash_prob > 0`, durable state never
     rolls back across a crash/recover cycle: raft commit indices and
     committed log prefixes, pbft committed slots and decided values,
     paxos learned values, dpos chains are monotone per round, per
     node — even as nodes churn through crash/recover cycles.
  3. **Determinism** — crash draws are pure counter functions of
     (seed, round, node), so chunked/grouped execution of a crashing
     run is bit-identical to the one-program run, and the telemetry
     counters (crashes/recoveries/nodes_down) agree too.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensus_tpu.core.config import Config
from consensus_tpu.network import runner, simulator, supervisor

from helpers import committed_prefixes_agree, run_cached, trace_raft_rounds

ADV = dict(drop_rate=0.1, partition_rate=0.05, churn_rate=0.05)
CRASH = dict(crash_prob=0.15, recover_prob=0.3)

CFGS = {
    "raft": Config(protocol="raft", n_nodes=5, n_rounds=48, n_sweeps=2,
                   log_capacity=32, max_entries=16, **ADV),
    "raft-sparse": Config(protocol="raft", n_nodes=16, max_active=4,
                          n_rounds=40, n_sweeps=2, log_capacity=16,
                          max_entries=8, **ADV),
    "pbft": Config(protocol="pbft", f=1, n_nodes=4, n_rounds=24,
                   log_capacity=8, **ADV),
    "pbft-bcast": Config(protocol="pbft", fault_model="bcast", f=2,
                         n_nodes=7, n_rounds=24, log_capacity=8, **ADV),
    "paxos": Config(protocol="paxos", n_nodes=7, n_rounds=24,
                    log_capacity=8, **ADV),
    "dpos": Config(protocol="dpos", n_nodes=24, n_rounds=32,
                   log_capacity=48, n_candidates=8, n_producers=3,
                   epoch_len=8, **ADV),
}


def _crashed(cfg, **extra):
    return dataclasses.replace(cfg, **{**CRASH, **extra})


def _trace_rounds(cfg):
    """Per-round extract() snapshots, [R, B, ...] — the monotonicity
    probe (final states cannot show a mid-run rollback)."""
    eng = simulator.engine_def(cfg)
    seeds = jnp.asarray(runner.make_seeds(cfg))

    def go(seed):
        def body(c, r):
            c2 = eng.round_fn(cfg, c, r)
            return c2, eng.extract(c2)
        _, out = jax.lax.scan(body, eng.make_carry(cfg, seed),
                              jnp.arange(cfg.n_rounds, dtype=jnp.int32))
        return out

    out = jax.jit(jax.vmap(go, in_axes=0, out_axes=1))(seeds)
    return {k: np.asarray(v) for k, v in out.items()}


# --- 1. crash_prob = 0 is digest-neutral ------------------------------------

@pytest.mark.parametrize("name", list(CFGS))
def test_crash_off_is_digest_neutral(name):
    """Explicitly-zero crash_prob (even with recover_prob/max_crashed
    set) is bit-identical to the plain config — the §6c block must be a
    static no-op, not a near-no-op."""
    cfg = CFGS[name]
    off = simulator.run(dataclasses.replace(
        cfg, crash_prob=0.0, recover_prob=0.5, max_crashed=2), warmup=False)
    assert off.payload == run_cached(cfg).payload


@pytest.mark.parametrize("repl", [dict(scan_chunk=7), dict(sweep_chunk=1)],
                         ids=["scan_chunk", "sweep_chunk"])
@pytest.mark.parametrize("name", list(CFGS))
def test_crash_off_neutral_under_chunking(name, repl):
    cfg = dataclasses.replace(CFGS[name], crash_prob=0.0, recover_prob=0.5)
    assert simulator.run(dataclasses.replace(cfg, **repl),
                         warmup=False).payload == run_cached(
        CFGS[name]).payload


# --- 2. durable state never rolls back --------------------------------------

def _assert_prefix_stable(count, vals, what):
    """vals[r, b, i, :count[r, b, i]] must be unchanged at r+1."""
    R = count.shape[0]
    L = vals.shape[-1]
    karange = np.arange(L)
    for r in range(R - 1):
        mask = karange[None, None, :] < count[r][..., None]
        np.testing.assert_array_equal(
            np.where(mask, vals[r], 0), np.where(mask, vals[r + 1], 0),
            err_msg=f"{what}: decided prefix changed after round {r}")


@pytest.mark.parametrize("name", ["raft", "raft-sparse"])
def test_raft_commit_durable_across_crashes(name):
    cfg = _crashed(CFGS[name])
    tr = _trace_rounds(cfg)
    assert (np.diff(tr["commit"], axis=0) >= 0).all(), \
        "commit index rolled back across a crash/recover cycle"
    _assert_prefix_stable(tr["commit"], tr["log_val"], name)
    _assert_prefix_stable(tr["commit"], tr["log_term"], name)


@pytest.mark.parametrize("name", ["pbft", "pbft-bcast"])
def test_pbft_committed_durable_across_crashes(name):
    cfg = _crashed(CFGS[name])
    tr = _trace_rounds(cfg)
    com = tr["committed"]
    assert (com[:-1] <= com[1:]).all(), "a committed slot un-committed"
    for r in range(cfg.n_rounds - 1):
        np.testing.assert_array_equal(
            np.where(com[r], tr["dval"][r], 0),
            np.where(com[r], tr["dval"][r + 1], 0),
            err_msg=f"{name}: decided value changed after round {r}")


def test_paxos_learned_durable_across_crashes():
    cfg = _crashed(CFGS["paxos"])
    tr = _trace_rounds(cfg)
    lm = tr["learned_mask"]
    assert (lm[:-1] <= lm[1:]).all(), "a learned slot was forgotten"
    for r in range(cfg.n_rounds - 1):
        np.testing.assert_array_equal(
            np.where(lm[r], tr["learned_val"][r], 0),
            np.where(lm[r], tr["learned_val"][r + 1], 0),
            err_msg=f"learned value changed after round {r}")


def test_dpos_chain_durable_across_crashes():
    cfg = _crashed(CFGS["dpos"])
    tr = _trace_rounds(cfg)
    assert (np.diff(tr["chain_len"], axis=0) >= 0).all()
    _assert_prefix_stable(tr["chain_len"], tr["chain_p"], "dpos chain_p")
    _assert_prefix_stable(tr["chain_len"], tr["chain_r"], "dpos chain_r")


def test_paxos_no_conflicting_learned_values():
    """Agreement survives the promise-bookkeeping reset (SPEC §6c's
    volatility argument: ballots strictly increase across rounds, so a
    forgotten promise can never admit a lower ballot)."""
    cfg = _crashed(CFGS["paxos"])
    res = simulator.run(cfg, warmup=False)
    # pack_sparse decided records: rec_a = slot ids, rec_b = values.
    for b in range(cfg.n_sweeps):
        slot_val: dict[int, int] = {}
        for i in range(cfg.n_nodes):
            c = int(res.counts[b, i])
            for s, v in zip(res.rec_a[b, i, :c], res.rec_b[b, i, :c]):
                assert slot_val.setdefault(int(s), int(v)) == int(v), \
                    f"sweep {b}: two learned values for slot {s}"


def test_raft_state_machine_safety_under_crashes():
    cfg = _crashed(CFGS["raft"])
    res = simulator.run(cfg, warmup=False)
    for b in range(cfg.n_sweeps):
        assert committed_prefixes_agree(res, list(range(cfg.n_nodes)), b)


# --- 3. determinism: chunking + telemetry -----------------------------------

@pytest.mark.parametrize("repl", [dict(scan_chunk=7), dict(sweep_chunk=1)],
                         ids=["scan_chunk", "sweep_chunk"])
def test_crashing_run_invariant_to_chunking(repl):
    cfg = _crashed(CFGS["raft"])
    base = simulator.run(cfg, warmup=False, telemetry=True, stats={})
    got = simulator.run(dataclasses.replace(cfg, **repl), warmup=False,
                        telemetry=True, stats={})
    assert got.payload == base.payload
    for k, v in base.extras["telemetry"]["per_sweep"].items():
        np.testing.assert_array_equal(
            got.extras["telemetry"]["per_sweep"][k], v, err_msg=k)


@pytest.mark.parametrize("name", list(CFGS))
def test_crash_telemetry_counters_flow(name):
    cfg = _crashed(CFGS[name])
    res = simulator.run(cfg, warmup=False, telemetry=True, stats={})
    t = res.extras["telemetry"]["totals"]
    assert t["crashes"] > 0, "adversary enabled but nobody ever crashed"
    # Every recovery needs a prior crash; every crash is down >= 1 round.
    assert t["recoveries"] <= t["crashes"] <= t["nodes_down"]


def test_crash_telemetry_zero_when_disabled():
    res = simulator.run(CFGS["raft"], warmup=False, telemetry=True, stats={})
    t = res.extras["telemetry"]["totals"]
    assert t["crashes"] == t["recoveries"] == t["nodes_down"] == 0


def test_max_crashed_caps_simultaneous_downs():
    cfg = _crashed(CFGS["raft"], crash_prob=0.9, recover_prob=0.05,
                   max_crashed=2)
    tr = trace_raft_rounds(cfg, None)
    per_round_down = tr["down"].sum(axis=2)          # [R, B]
    assert per_round_down.max() <= 2
    assert per_round_down.max() == 2, "cap never reached — test is vacuous"


def test_crash_checkpoint_resume_bit_identical(tmp_path):
    """The execution-layer and protocol-layer fault models compose: a
    checkpointed crashing run resumes bit-identically (the down mask
    rides the carry through the snapshot)."""
    cfg = _crashed(CFGS["raft"], scan_chunk=8)
    base = simulator.run(cfg, warmup=False)
    ck = tmp_path / "ck.npz"
    eng = simulator.engine_def(cfg)
    seeds = jnp.asarray(runner.make_seeds(cfg))
    carry = runner._init_jit(cfg, eng, seeds)
    carry = runner._chunk_jit(cfg, eng, 16, carry, jnp.int32(0))
    runner.save_checkpoint(ck, cfg, carry, 16)
    resumed = simulator.run(cfg, warmup=False, checkpoint_path=str(ck),
                            resume=True, stats=(stats := {}))
    assert stats["start_round"] == 16
    assert resumed.payload == base.payload


# --- config / CLI surface ----------------------------------------------------

def test_crash_accepted_on_cpu_engine():
    """SPEC §6c is mirrored scalar-for-scalar in the oracle since the
    adversary-library PR: crash_prob > 0 on engine="cpu" is legal and
    byte-differential (tests/test_adversary_lib.py carries the full
    parity grid)."""
    cfg = dataclasses.replace(_crashed(CFGS["raft"]), engine="cpu")
    assert simulator.run(cfg, warmup=False).payload \
        == run_cached(_crashed(CFGS["raft"])).payload


def test_config_rejects_bad_max_crashed():
    with pytest.raises(ValueError, match="max_crashed"):
        Config(protocol="raft", n_nodes=5, max_crashed=6)
    with pytest.raises(ValueError, match="max_crashed"):
        Config(protocol="raft", n_nodes=5, max_crashed=-1)


def test_supervisor_allows_fallback_cpu_with_crashes():
    """The old fallback-rejects-crash guard is LIFTED (the oracle
    mirrors §6c): a supervised crashing run may degrade, and the
    degraded digest matches (tests/test_adversary_lib.py drives the
    actual degradation path; here the no-failure supervised run)."""
    res = supervisor.supervised_run(_crashed(CFGS["raft"]),
                                    fallback_cpu=True, retries=0)
    assert not res.extras["run_report"]["fallback_used"]
    assert res.payload == run_cached(_crashed(CFGS["raft"])).payload


def test_config_json_roundtrips_crash_fields():
    cfg = _crashed(CFGS["raft"], max_crashed=3)
    assert Config.from_json(cfg.to_json()) == cfg
    # Pre-§6c config dicts load with the adversary off.
    old = {"protocol": "raft", "n_nodes": 5}
    cfg2 = Config.from_json(__import__("json").dumps(old))
    assert cfg2.crash_prob == 0.0 and cfg2.max_crashed == 0
