"""Mesh × sweep_chunk × checkpoint interplay at benchmark-class shapes
(VERDICT r5 weak #4): trace-time coverage on the 8-virtual-CPU mesh.

The three features compose on the flagship configs only on a real chip
— never in CI, where executing a 100k-node round is minutes. But every
error class this interplay has produced is a TRACE-time error (sharding
constraints that don't divide, group configs the mesh rejects, carry
pspec/structure mismatches under jit), so these tests drive the
PRODUCTION entry points exactly to the point where XLA lowering begins
and no further:

  * `_sweep_groups` + `_check_groups` — the grouping layer must accept
    the flagship shapes (incl. the ragged tail) and fail fast on an
    unshardable tail BEFORE any device time is spent;
  * `runner._init_jit.lower` / `runner._chunk_jit.lower` per group on
    the (sweep, node) mesh — full jit tracing + GSPMD sharding-spec
    resolution over ShapeDtypeStructs, zero FLOPs executed, no timing;
  * the grouped-checkpoint layout (`group_checkpoint_path`,
    `write/read_group_manifest`) against the SAME flagship configs +
    seed vectors, plus the checkpoint_path+sweep_chunk rejection.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from consensus_tpu.core.config import Config
from consensus_tpu.network import runner, simulator
from consensus_tpu.parallel.mesh import make_mesh

ADV = dict(drop_rate=0.01, churn_rate=0.001)

# Benchmark-class shapes (run_benchmarks.CONFIGS semantics) with a
# sweep_chunk that groups 8 sweeps into 4+4 and a (2, 4) sweep × node
# mesh — the composition the flagship runs will use on a real v5e-8.
FLAGSHIPS = {
    "raft-100k-cap8": Config(protocol="raft", n_nodes=100_000, n_rounds=64,
                             n_sweeps=8, log_capacity=128, max_entries=100,
                             max_active=8, seed=6, sweep_chunk=4,
                             mesh_shape=(2, 4), scan_chunk=32, **ADV),
    "pbft-100k-bcast": Config(protocol="pbft", fault_model="bcast",
                              f=33_333, n_nodes=100_000, n_rounds=64,
                              n_sweeps=8, log_capacity=16, seed=7,
                              sweep_chunk=4, mesh_shape=(2, 4),
                              scan_chunk=32, **ADV),
    # dpos-100k runs 1 sweep — node-axis-only mesh, no grouping.
    "dpos-100k": Config(protocol="dpos", n_nodes=100_000, n_rounds=256,
                        n_sweeps=1, log_capacity=256, n_candidates=1024,
                        n_producers=21, epoch_len=32, seed=5,
                        mesh_shape=(1, 8), scan_chunk=64, **ADV),
}


def _carry_struct(cfg, eng, mesh):
    """ShapeDtypeStruct pytree of the batched carry — via eval_shape, so
    no 100k-node buffer is ever allocated."""
    seeds = jax.ShapeDtypeStruct((cfg.n_sweeps,), jnp.uint32)
    return jax.eval_shape(
        lambda s: jax.vmap(lambda x: eng.make_carry(cfg, x))(s), seeds)


def _lower_one_chunk(cfg, eng, mesh) -> str:
    """Trace + lower one production round-loop chunk (runner._chunk_jit,
    the exact jit the benchmarks dispatch) on the mesh. Returns the
    StableHLO text so callers can assert it actually lowered."""
    carry = _carry_struct(cfg, eng, mesh)
    r0 = jax.ShapeDtypeStruct((), jnp.int32)
    chunk = cfg.scan_chunk or cfg.n_rounds
    lowered = runner._chunk_jit.lower(cfg, eng, chunk, carry, r0, mesh=mesh)
    return lowered.as_text()


@pytest.mark.parametrize("name", sorted(FLAGSHIPS))
def test_flagship_groups_lower_on_mesh(name):
    cfg = FLAGSHIPS[name]
    groups = runner._sweep_groups(cfg)
    if cfg.sweep_chunk:
        assert groups is not None and len(groups) == 2
        # Fail-fast divisibility check over EVERY group incl. the tail.
        mesh = runner._check_groups(cfg, groups, None)
        subs = [sub for sub, _ in groups]
    else:
        assert groups is None
        mesh = make_mesh(cfg.mesh_shape)
        subs = [dataclasses.replace(cfg, mesh_shape=cfg.mesh_shape)]
    seen = set()
    for sub in subs:
        key = (sub.n_sweeps, sub.n_nodes)
        if key in seen:
            continue  # identical shape ⇒ identical trace; don't re-pay it
        seen.add(key)
        eng = simulator.engine_def(sub)
        txt = _lower_one_chunk(sub, eng, mesh)
        assert "stablehlo" in txt or "module" in txt


def test_ragged_tail_mesh_mismatch_fails_fast():
    # 8 sweeps in chunks of 3 → tail group of 2... but chunk 3 itself is
    # not divisible by the 2-way sweep axis: _check_groups must reject
    # BEFORE any group runs (the error names the divisibility).
    cfg = dataclasses.replace(FLAGSHIPS["raft-100k-cap8"], sweep_chunk=3)
    groups = runner._sweep_groups(cfg)
    assert groups is not None
    with pytest.raises(ValueError, match="not divisible"):
        runner._check_groups(cfg, groups, None)


def test_grouped_checkpoint_layout_roundtrip(tmp_path):
    # The grouped-resume layout at the flagship config: per-group
    # subdirectories + a config/seed-guarded manifest (host-only; no
    # simulation runs).
    cfg = FLAGSHIPS["raft-100k-cap8"]
    seeds = runner.make_seeds(cfg)
    root = tmp_path / "groups"
    paths = [runner.group_checkpoint_path(root, gi) for gi in range(2)]
    assert len({p.parent for p in paths}) == 2  # no rotation collisions
    runner.write_group_manifest(root, cfg, seeds, [0], 2)
    assert runner.read_group_manifest(root, cfg, seeds) == [0]
    runner.write_group_manifest(root, cfg, seeds, [0, 1], 2)
    assert runner.read_group_manifest(root, cfg, seeds) == [0, 1]
    # A different seed vector or config is NOT this run's manifest.
    other = np.asarray(seeds) + np.uint32(1)
    assert runner.read_group_manifest(root, cfg, other) is None
    assert runner.read_group_manifest(
        root, dataclasses.replace(cfg, seed=cfg.seed + 1), None) is None


def test_checkpoint_path_with_sweep_chunk_still_rejected(tmp_path):
    # One rotation set cannot hold N groups' snapshots; the rejection
    # must hold at the flagship shape too (and point at group_dir).
    cfg = FLAGSHIPS["raft-100k-cap8"]
    eng = simulator.engine_def(cfg)
    with pytest.raises(ValueError, match="group_dir"):
        runner.run(cfg, eng, checkpoint_path=tmp_path / "ck.npz")
